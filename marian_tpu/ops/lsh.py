"""LSH approximate-kNN output vocabulary search (--output-approx-knn k nbits).

Rebuild of reference src/data/shortlist.h/.cpp :: LSHShortlist + the vendored
faiss IndexLSH subset (src/3rd_party/faiss). Semantics kept: random-
hyperplane signatures over the output embedding rows; at every decode step
the k rows whose signatures are hamming-closest to the decoder state's
signature form the candidate set, and only those k logits are computed
exactly.

TPU redesign (vs faiss's CPU bucket probing): everything is dense, static-
shaped tensor math inside the jitted decode step —

    sign bits      x @ planes.T > 0        → jnp.packbits   [.., nbits/8]
    hamming        popcount(xor)           → lax.population_count + sum
    candidates     lax.top_k(-hamming, k)  (the beam-search top-k machinery)
    exact logits   gather k table rows → batched dot → scatter into [V]
                   at -1e9 elsewhere, so beam search runs unchanged in
                   full-vocab coordinates.

EOS always gets its exact logit (a hypothesis must be able to finish even
when EOS's signature is far — the reference forces EOS/UNK into the LSH
shortlist too).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e9
_LSH_SEED = 0x15A9  # fixed: signatures must match across processes/calls


def lsh_planes(dim: int, nbits: int, dtype=jnp.float32) -> jax.Array:
    """[nbits, dim] random hyperplanes (deterministic seed: an index built
    at save time stays valid at load time)."""
    key = jax.random.key(_LSH_SEED)
    return jax.random.normal(key, (nbits, dim), dtype)


def pack_signatures(x: jax.Array, planes: jax.Array) -> jax.Array:
    """Sign-bit signatures of rows of x [N, D] → packed uint8 [N, nbits/8]."""
    bits = (x.astype(planes.dtype) @ planes.T) > 0
    return jnp.packbits(bits.astype(jnp.uint8), axis=-1)


def build_index(table: jax.Array, nbits: int) -> Tuple[jax.Array, jax.Array]:
    """(planes [nbits, D], signatures [V, nbits/8]) for an output table
    [V, D]. Pure function of the params — safe to compute under jit."""
    planes = lsh_planes(table.shape[-1], nbits)
    return planes, pack_signatures(table, planes)


def hamming_topk(x: jax.Array, planes: jax.Array, signatures: jax.Array,
                 k: int) -> jax.Array:
    """Indices [N, k] of the k hamming-nearest table rows for each row of
    x [N, D]. The [N, V, nbits/8] xor intermediate is fine at decode-step
    batch sizes (N = batch×beam)."""
    xs = pack_signatures(x, planes)                       # [N, W]
    xored = jnp.bitwise_xor(xs[:, None, :], signatures[None, :, :])
    ham = jax.lax.population_count(xored).astype(jnp.int32).sum(-1)  # [N, V]
    _, idx = jax.lax.top_k(-ham, k)
    return idx


def lsh_logits(x: jax.Array, table: jax.Array, bias: jax.Array,
               planes: jax.Array, signatures: jax.Array, k: int,
               eos_id: int = 0) -> jax.Array:
    """Approximate output logits [N, V]: exact dot products on the k LSH
    candidates (+ EOS), NEG_INF elsewhere. x [N, D], table [V, D], bias [V].
    """
    n = x.shape[0]
    v = table.shape[0]
    idx = hamming_topk(x, planes, signatures, k)          # [N, k]
    rows = table[idx]                                     # [N, k, D]
    lg = jnp.einsum("nd,nkd->nk", x.astype(jnp.float32),
                    rows.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    lg = lg + bias[idx].astype(jnp.float32)
    out = jnp.full((n, v), NEG_INF, jnp.float32)
    out = out.at[jnp.arange(n)[:, None], idx].set(lg)
    # EOS exactly, always
    eos_lg = (x.astype(jnp.float32) @ table[eos_id].astype(jnp.float32)
              + bias[eos_id].astype(jnp.float32))
    return out.at[:, eos_id].set(eos_lg)
