"""Scaled dot-product attention — the MXU hot path.

The reference implements attention as strided-batched cuBLAS GEMMs +
masked-softmax kernels (src/tensors/gpu/prod.cpp :: ProdBatched,
src/models/transformer.h :: MultiHead). Here the dense path is einsum-based
(XLA maps it straight onto the MXU and fuses mask+softmax); a Pallas
flash-attention kernel (ops/pallas/flash_attention.py) takes over for long
sequences where the O(L²) score tensor would blow HBM bandwidth.

Shapes are batch-major: q [B, H, Tq, Dh], k/v [B, H, Tk, Dh],
mask [B, 1, Tq, Tk] (1 = attend).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .ops import NEG_INF, dropout as _dropout


def dense_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    mask: Optional[jax.Array] = None,
                    dropout_rate: float = 0.0,
                    dropout_key: Optional[jax.Array] = None,
                    deterministic: bool = True) -> jax.Array:
    """Returns ([B, H, Tq, Dh] context, attention weights are not returned;
    use dense_attention_with_weights when alignments are needed)."""
    out, _ = dense_attention_with_weights(
        q, k, v, mask, dropout_rate, dropout_key, deterministic,
        return_weights=False)
    return out


def dense_attention_with_weights(q, k, v, mask=None, dropout_rate=0.0,
                                 dropout_key=None, deterministic=True,
                                 return_weights=True):
    dh = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(dh, jnp.float32)).astype(q.dtype)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q * scale, k,
                        preferred_element_type=jnp.float32)
    if mask is not None:
        scores = scores + (1.0 - mask.astype(scores.dtype)) * NEG_INF
    weights = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if dropout_rate > 0.0 and not deterministic:
        weights = _dropout(weights, dropout_rate, dropout_key)
    out = jnp.einsum("bhqk,bhkd->bhqd", weights, v,
                     preferred_element_type=jnp.float32).astype(q.dtype)
    return out, (weights if return_weights else None)


def attention(q: jax.Array, k: jax.Array, v: jax.Array,
              mask: Optional[jax.Array] = None,
              kv_mask: Optional[jax.Array] = None,
              causal: bool = False,
              dropout_rate: float = 0.0,
              dropout_key: Optional[jax.Array] = None,
              deterministic: bool = True,
              return_weights: bool = False,
              flash: str = "auto",
              flash_min_len: Optional[int] = None,
              packed: str = "auto",
              packed_max_len: Optional[int] = None):
    """Attention dispatcher: dense (XLA-fused einsum) vs the two Pallas
    kernels — flash (long sequences) and head-packed (short sequences).

    `mask` is the general [B,1,Tq,Tk] dense mask; `kv_mask` [B,Tk] + `causal`
    is the structured form both Pallas kernels understand. Callers that can,
    pass both. A kernel is picked when it is (a) allowed (its gate = auto|on),
    (b) applicable (no returned weights, no active attention dropout, a
    structured mask describing the dense one, multi-query step), and (c) for
    "auto", worth it on its regime: flash when the sequence is long enough
    that streaming K/V blocks beats one fused dense batch matmul (crossover
    measured on v5e ~1-2k); packed when the sequence is SHORT enough that
    the dh=64-contraction einsums underfill the 128x128 MXU (the r5
    truth-table 21.7%/30.6% geometry, docs/PERFORMANCE.md) and a head
    group actually packs (g >= 2, i.e. dh <= 64). Packed 'auto' engages on
    the TPU backend only — in interpret mode it would just be a slower
    dense path. Flash owns the overlap: its gate is checked first."""
    if flash_min_len is None:
        # default crossover; --auto-tune rebinds it (ops/auto_tuner.py)
        from .auto_tuner import flash_threshold
        flash_min_len = flash_threshold()
    applicable = (
        not return_weights
        and (deterministic or dropout_rate == 0.0)
        and q.shape[-2] > 1
        and (kv_mask is not None or causal or mask is None))
    if applicable and flash != "off" and (
            flash == "on" or max(q.shape[-2], k.shape[-2]) >= flash_min_len):
        from .pallas.flash_attention import flash_attention
        return flash_attention(q, k, v, kv_mask=kv_mask, causal=causal), None
    if applicable and packed != "off":
        from .auto_tuner import packed_attention_max_t
        from .pallas.packed_attention import pack_group
        dh = q.shape[-1]
        cap = (packed_max_len if packed_max_len is not None
               else packed_attention_max_t(dh))
        fits = max(q.shape[-2], k.shape[-2]) <= cap
        wins = pack_group(q.shape[1], dh) >= 2 \
            and jax.default_backend() == "tpu"
        if fits and (packed == "on" or wins):
            from .pallas.packed_attention import packed_attention
            return packed_attention(q, k, v, kv_mask=kv_mask,
                                    causal=causal), None
    return dense_attention_with_weights(
        q, k, v, mask, dropout_rate, dropout_key, deterministic,
        return_weights)


def causal_mask(length: int, dtype=jnp.float32) -> jax.Array:
    """[1, 1, T, T] future mask (reference: transformer.h triangle mask)."""
    m = jnp.tril(jnp.ones((length, length), dtype=dtype))
    return m[None, None, :, :]


def combine_masks(*masks: Optional[jax.Array]) -> Optional[jax.Array]:
    out = None
    for m in masks:
        if m is None:
            continue
        out = m if out is None else out * m
    return out
