"""RNN cell zoo — GRU (Nematus variant), LSTM, SSRU — as pure functions
designed for `lax.scan`.

Rebuild of reference src/rnn/cells.h (GRU/LSTM/SSRU) and src/rnn/rnn.h
(RNN runner). The reference runs one fused CUDA kernel per cell step
(gpu::GRUFastForward); the TPU design instead splits each cell into

  1. an *input projection* computed for the WHOLE sequence in one large
     [B*T, in] x [in, G*D] matmul before the scan (MXU-friendly — this is
     where nearly all the FLOPs are), and
  2. a small per-step recurrence inside `lax.scan` (only the h-dependent
     matmul, which is irreducibly sequential).

SSRU has NO h-dependent matmul, so its recurrence is a first-order linear
scan c_t = f_t * c_{t-1} + i_t that runs as a PARALLEL prefix scan
(`lax.associative_scan`) over the time axis — O(log T) depth on TPU instead
of O(T). This is why Marian uses SSRU for fast decoders
(src/rnn/cells.h :: SSRU); on TPU it additionally parallelizes training.

Conventions:
- cell params live in a FLAT dict under a string prefix (matches the model
  param style); weights are [in, out], applied as x @ W;
- cell state is a dict with keys from ("h", "c");
- a cell with `dim_in == 0` is a *transition* cell (deep-transition RNNs,
  reference: rnn.h stacked transition cells): no input matrix, the input
  projection is just the bias;
- optional layer-normalization normalizes the input- and state-projections
  separately, scale-only (reference: cells.h layer-norm variants).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..layers import initializers as inits
from .ops import affine, layer_norm

Params = Dict[str, jax.Array]
State = Dict[str, jax.Array]


def _ln(x: jax.Array, params: Params, name: str, enabled: bool) -> jax.Array:
    if not enabled or name not in params:
        return x
    return layer_norm(x, params[name])


class Cell:
    """Common interface: init / x_proj (whole-sequence input GEMM) / step."""

    kind: str = ""
    state_keys: Tuple[str, ...] = ("h",)
    n_gates: int = 1

    def __init__(self, dim_in: int, dim: int, ln: bool = False):
        self.dim_in = dim_in
        self.dim = dim
        self.ln = ln

    def init(self, key: jax.Array, params: Params, prefix: str) -> None:
        raise NotImplementedError

    def x_proj(self, params: Params, prefix: str,
               x: Optional[jax.Array]) -> jax.Array:
        raise NotImplementedError

    def step(self, params: Params, prefix: str, xp: jax.Array,
             state: State) -> Tuple[jax.Array, State]:
        raise NotImplementedError

    def init_state(self, batch: int, dtype) -> State:
        return {k: jnp.zeros((batch, self.dim), dtype) for k in self.state_keys}


class GRU(Cell):
    """Nematus-style GRU (reference: cells.h :: GRU):

        z = sigmoid(x Wz + h Uz)        (update gate)
        r = sigmoid(x Wr + h Ur)        (reset gate)
        h~ = tanh(x Wx + r * (h Ux))    (reset applied after the matmul)
        h' = z * h + (1 - z) * h~
    """

    kind = "gru"
    state_keys = ("h",)
    n_gates = 3

    def init(self, key, params, prefix):
        k = jax.random.split(key, 4)
        d = self.dim
        if self.dim_in > 0:
            params[f"{prefix}_W"] = inits.glorot_uniform(k[0], (self.dim_in, 3 * d))
        params[f"{prefix}_b"] = inits.zeros((1, 3 * d))
        params[f"{prefix}_U"] = inits.glorot_uniform(k[1], (d, 2 * d))
        params[f"{prefix}_Ux"] = inits.glorot_uniform(k[2], (d, d))
        if self.ln:
            params[f"{prefix}_W_ln_scale"] = inits.ones((1, 3 * d))
            params[f"{prefix}_U_ln_scale"] = inits.ones((1, 2 * d))
            params[f"{prefix}_Ux_ln_scale"] = inits.ones((1, d))

    def x_proj(self, params, prefix, x):
        b = params[f"{prefix}_b"]
        if x is None or self.dim_in == 0:
            return b
        xp = affine(x, params[f"{prefix}_W"])
        xp = _ln(xp, params, f"{prefix}_W_ln_scale", self.ln)
        return xp + b.astype(xp.dtype)

    def step(self, params, prefix, xp, state):
        h = state["h"]
        d = self.dim
        hu = affine(h, params[f"{prefix}_U"])
        hu = _ln(hu, params, f"{prefix}_U_ln_scale", self.ln)
        hx = affine(h, params[f"{prefix}_Ux"])
        hx = _ln(hx, params, f"{prefix}_Ux_ln_scale", self.ln)
        xz, xr, xh = xp[..., :d], xp[..., d:2 * d], xp[..., 2 * d:]
        hz, hr = hu[..., :d], hu[..., d:]
        z = jax.nn.sigmoid(xz + hz)
        r = jax.nn.sigmoid(xr + hr)
        hh = jnp.tanh(xh + r * hx)
        h2 = z * h + (1.0 - z) * hh   # mtlint: ok -- z is sigmoid(h-chain): same dtype as h by construction; the weak literal follows it
        return h2, {"h": h2}


class LSTM(Cell):
    """Standard LSTM (reference: cells.h :: LSTM): fused 4-gate projection,
    c' = f*c + i*tanh(g), h' = o*tanh(c')."""

    kind = "lstm"
    state_keys = ("h", "c")
    n_gates = 4

    def init(self, key, params, prefix):
        k = jax.random.split(key, 2)
        d = self.dim
        if self.dim_in > 0:
            params[f"{prefix}_W"] = inits.glorot_uniform(k[0], (self.dim_in, 4 * d))
        params[f"{prefix}_b"] = inits.zeros((1, 4 * d))
        params[f"{prefix}_U"] = inits.glorot_uniform(k[1], (d, 4 * d))
        if self.ln:
            params[f"{prefix}_W_ln_scale"] = inits.ones((1, 4 * d))
            params[f"{prefix}_U_ln_scale"] = inits.ones((1, 4 * d))

    def x_proj(self, params, prefix, x):
        b = params[f"{prefix}_b"]
        if x is None or self.dim_in == 0:
            return b
        xp = affine(x, params[f"{prefix}_W"])
        xp = _ln(xp, params, f"{prefix}_W_ln_scale", self.ln)
        return xp + b.astype(xp.dtype)

    def step(self, params, prefix, xp, state):
        h, c = state["h"], state["c"]
        d = self.dim
        hu = affine(h, params[f"{prefix}_U"])
        hu = _ln(hu, params, f"{prefix}_U_ln_scale", self.ln)
        g = xp + hu
        i = jax.nn.sigmoid(g[..., :d])
        f = jax.nn.sigmoid(g[..., d:2 * d])
        o = jax.nn.sigmoid(g[..., 2 * d:3 * d])
        cand = jnp.tanh(g[..., 3 * d:])
        c2 = f * c + i * cand
        h2 = o * jnp.tanh(c2)
        return h2, {"h": h2, "c": c2}


class SSRU(Cell):
    """Simpler Simple Recurrent Unit (reference: cells.h :: SSRU; Kim et al.
    "From Research to Production"):

        f = sigmoid(x Wf + bf)
        c' = f * c + (1 - f) * (x W)
        h  = relu(c')

    No h-dependent matmul → the whole-sequence path runs as a parallel
    prefix scan (`scan_linear_recurrence`)."""

    kind = "ssru"
    state_keys = ("c",)
    n_gates = 2

    def init(self, key, params, prefix):
        k = jax.random.split(key, 2)
        d = self.dim
        di = self.dim_in if self.dim_in > 0 else d
        params[f"{prefix}_W"] = inits.glorot_uniform(k[0], (di, d))
        params[f"{prefix}_Wf"] = inits.glorot_uniform(k[1], (di, d))
        params[f"{prefix}_bf"] = inits.zeros((1, d))
        if self.ln:
            params[f"{prefix}_W_ln_scale"] = inits.ones((1, d))

    def x_proj(self, params, prefix, x):
        if x is None or self.dim_in == 0:
            x = jnp.zeros((1, self.dim), params[f"{prefix}_bf"].dtype)
        xw = affine(x, params[f"{prefix}_W"])
        xw = _ln(xw, params, f"{prefix}_W_ln_scale", self.ln)
        f = jax.nn.sigmoid(affine(x, params[f"{prefix}_Wf"],
                                  params[f"{prefix}_bf"]))
        return jnp.concatenate([f, (1.0 - f) * xw], axis=-1)  # mtlint: ok -- f is sigmoid(affine(x)): same dtype as xw by construction

    def step(self, params, prefix, xp, state):
        d = self.dim
        f, inp = xp[..., :d], xp[..., d:]
        c2 = f * state["c"] + inp
        return jax.nn.relu(c2), {"c": c2}


CELLS = {"gru": GRU, "lstm": LSTM, "ssru": SSRU,
         "gru-nematus": GRU}


def make_cell(kind: str, dim_in: int, dim: int, ln: bool = False) -> Cell:
    try:
        return CELLS[kind](dim_in, dim, ln)
    except KeyError:
        raise NotImplementedError(f"RNN cell '{kind}'") from None


def scan_linear_recurrence(f: jax.Array, inp: jax.Array,
                           c0: jax.Array) -> jax.Array:
    """Parallel prefix scan for c_t = f_t * c_{t-1} + inp_t over axis 0
    (time-major [T, B, D]). Composition of two affine maps is affine:
    (a2, b2) ∘ (a1, b1) = (a1*a2, a2*b1 + b2)."""

    def combine(x, y):
        a1, b1 = x
        a2, b2 = y
        return a1 * a2, a2 * b1 + b2

    inp0 = inp.at[0].add(f[0] * c0)
    _, c = jax.lax.associative_scan(combine, (f, inp0), axis=0)
    return c


def chain_step(chain, params: Params, xp: jax.Array,
               state: State) -> Tuple[jax.Array, State]:
    """One deep-transition step: ONE recurrent state flows through the cell
    chain (reference: rnn.h stacked transition cells / Nematus deep
    transition). `chain` = [(prefix, cell)]; the first cell consumes the
    (precomputed) input projection `xp`, the rest are bias-only transition
    cells operating on the running state."""
    out = None
    for i, (prefix, cell) in enumerate(chain):
        cxp = xp if i == 0 else cell.x_proj(params, prefix, None)
        out, state = cell.step(params, prefix, cxp, state)
    return out, state


def run_layer(chain, params: Params,
              xs: jax.Array, mask: Optional[jax.Array],
              state0: Optional[State] = None,
              reverse: bool = False) -> Tuple[jax.Array, State]:
    """Run a deep-transition cell chain over a [B, T, in] sequence →
    ([B, T, D] outputs, final state). `chain` is [(prefix, cell)] (a single
    cell is the depth-1 case). `mask` [B, T] (1 = real token): on padding
    the state carries through unchanged and the output is zeroed
    (reference: rnn.h masked transitions). `reverse=True` scans
    right-to-left (backward encoder)."""
    if isinstance(chain, tuple) and len(chain) == 2 and isinstance(chain[0], str):
        chain = [chain]
    (prefix0, cell0) = chain[0]
    b, t = xs.shape[0], xs.shape[1]
    dtype = xs.dtype
    xp_all = cell0.x_proj(params, prefix0, xs)         # [B, T, G*D] big GEMM
    if xp_all.ndim == 2:                               # transition: bias only
        xp_all = jnp.broadcast_to(xp_all[None, :, :], (b, t, xp_all.shape[-1]))
    state = state0 or cell0.init_state(b, dtype)

    xp_tm = jnp.swapaxes(xp_all, 0, 1)                 # [T, B, G*D]
    m_tm = (jnp.swapaxes(mask, 0, 1)[..., None].astype(dtype)
            if mask is not None else None)

    if cell0.kind == "ssru" and state0 is None and len(chain) == 1:
        # parallel linear recurrence — no sequential scan at all
        d = cell0.dim
        f, inp = xp_tm[..., :d], xp_tm[..., d:]
        if m_tm is not None:
            # pad steps: c_t = c_{t-1}  (f=1, inp=0)
            f = jnp.where(m_tm > 0, f, jnp.ones_like(f))
            inp = jnp.where(m_tm > 0, inp, jnp.zeros_like(inp))
        if reverse:
            f, inp = f[::-1], inp[::-1]
        c = scan_linear_recurrence(f, inp, jnp.zeros((b, d), dtype))
        if reverse:
            c = c[::-1]
        out = jax.nn.relu(c)
        if m_tm is not None:
            out = out * m_tm
        final = {"c": c[-1] if not reverse else c[0]}
        return jnp.swapaxes(out, 0, 1), final

    def step_fn(carry, inputs):
        xp, m = inputs
        out, new_state = chain_step(chain, params, xp, carry)
        if m is not None:
            new_state = {k: m * new_state[k] + (1.0 - m) * carry[k]
                         for k in new_state}
            out = out * m
        return new_state, out

    if m_tm is None:
        final, outs = jax.lax.scan(
            lambda c, xp: step_fn(c, (xp, None)), state, xp_tm,
            reverse=reverse)
    else:
        final, outs = jax.lax.scan(step_fn, state, (xp_tm, m_tm),
                                   reverse=reverse)
    return jnp.swapaxes(outs, 0, 1), final
