"""Core tensor ops. The reference implements these as hand-written CUDA
kernels (src/tensors/gpu/tensor_operators.cu, element.cu, add_all.cu); here
each is a few lines of jnp that XLA fuses into the surrounding computation —
the per-node kernel dispatch the reference does at runtime collapses into one
compiled program (SURVEY.md §2.3/§2.4).

Numerics conventions kept from the reference:
- layer_norm uses epsilon inside sqrt(var + eps) (gpu::LayerNormalization);
- dropout uses inverted scaling (mask / keep_prob) with explicit PRNG keys
  (the reference's cuRAND bernoulli nodes become functional masks);
- masked softmax adds a large negative to masked logits pre-softmax.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e9  # large-negative mask value; safe in bf16 (min normal ~ -3.4e38)


def layer_norm(x: jax.Array, scale: jax.Array, bias: Optional[jax.Array] = None,
               eps: float = 1e-9) -> jax.Array:
    """LayerNorm over the last axis (reference: gpu::LayerNormalization;
    Marian's default eps is 1e-9)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    y = y * scale.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(dtype)


def rms_norm(x: jax.Array, scale: jax.Array, bias: Optional[jax.Array] = None,
             eps: float = 1e-9) -> jax.Array:
    """RMSNorm (reference: rmsNorm in expression_operators.cpp)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(ms + eps) * scale.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(dtype)


def dropout(x: jax.Array, rate: float, key: Optional[jax.Array],
            deterministic: bool = False) -> jax.Array:
    """Inverted dropout with explicit key (reference: dropout nodes backed by
    cuRAND bernoulli; PRNG-key discipline replaces device RNG state)."""
    if deterministic or rate <= 0.0 or key is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


def gelu(x: jax.Array) -> jax.Array:
    return jax.nn.gelu(x)


def swish(x: jax.Array) -> jax.Array:
    return x * jax.nn.sigmoid(x)


ACTIVATIONS = {
    "relu": jax.nn.relu,
    "swish": swish,
    "gelu": gelu,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
}


def activation(name: str):
    try:
        return ACTIVATIONS[name]
    except KeyError:
        raise ValueError(f"Unknown activation '{name}'") from None


@jax.custom_vjp
def logits_matmul(x: jax.Array, w: jax.Array) -> jax.Array:
    """x @ w emitting f32 (softmax/CE wants f32 logits) whose BACKWARD
    GEMMs run at the bf16 MXU rate.

    Without this, the [.., V] f32 logits cotangent forces the two VJP
    transpose dots (dx and dW — the largest GEMMs in the whole step, V
    = 32k wide) to run as f32xf32 matmuls: ~1/4 the MXU rate on v5e.
    The r5 HLO audit (scripts/audit_backward_dots.py) measured exactly
    two f32xf32 dots at 10.4% of step FLOPs ≈ ~30% of ideal step time —
    the bulk of VERDICT r4's "backward GEMMs at ~20% of roofline".

    The fix: round the cotangent to the compute dtype (bf16) once,
    then both backward dots are bf16xbf16 with f32 MXU accumulation.
    One extra rounding of the gradient signal (~2^-9 relative) against
    a 4x throughput win on the step's biggest GEMMs — the standard
    mixed-precision discipline (grads round through bf16 anyway
    wherever they cross a cast_params boundary).

    NOTE: this cotangent rounding follows the COMPUTE dtype (x.dtype)
    and applies regardless of --gradient-dtype — with bf16 compute,
    ``--gradient-dtype float32`` still sees the logits cotangent round
    through bf16 here (the flag only controls the dtype gradients are
    STORED/reduced in downstream). Documented in the --gradient-dtype
    help and docs/PERFORMANCE.md.

    x: [.., d] compute dtype; w: [d, V] compute dtype. Out: [.., V] f32.
    """
    return jax.lax.dot_general(x, w, (((x.ndim - 1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _logits_matmul_fwd(x, w):
    return logits_matmul(x, w), (x, w)


def _logits_matmul_bwd(res, g):
    x, w = res
    g16 = g.astype(x.dtype)
    dx = jax.lax.dot_general(g16, w, (((g16.ndim - 1,), (1,)), ((), ())),
                             preferred_element_type=x.dtype)
    lead = tuple(range(x.ndim - 1))
    dw = jax.lax.dot_general(x, g16, ((lead, lead), ((), ())),
                             preferred_element_type=jnp.float32)
    return dx, dw.astype(w.dtype)


logits_matmul.defvjp(_logits_matmul_fwd, _logits_matmul_bwd)


def affine(x: jax.Array, w, b: Optional[jax.Array] = None) -> jax.Array:
    """x @ w + b (reference: gpu::Affine / cublasLt fused bias). XLA fuses the
    bias add; weights stored [in, out] like Marian. Quantized (QTensor)
    weights from marian-conv run as int8×int8 MXU matmuls."""
    from .quantization import QTensor, int8_affine
    if isinstance(w, QTensor):
        return int8_affine(x, w, b)
    y = jnp.dot(x, w.astype(x.dtype), preferred_element_type=x.dtype)
    if b is not None:
        y = y + b.astype(x.dtype)
    return y


def masked_log_softmax(logits: jax.Array, mask: Optional[jax.Array] = None,
                       axis: int = -1) -> jax.Array:
    if mask is not None:
        logits = jnp.where(mask > 0, logits, NEG_INF)
    return jax.nn.log_softmax(logits, axis=axis)


def masked_softmax(logits: jax.Array, mask: Optional[jax.Array] = None,
                   axis: int = -1) -> jax.Array:
    """Softmax with additive log-mask (reference: gpu::Softmax with mask).
    The mask is pinned to the logits dtype before the arithmetic: masks
    are routinely built f32 (causal_mask's default), and an f32 mask would
    silently promote the whole bf16 softmax chain (mtlint MT-DTYPE-LITERAL;
    0/1 mask values are exact in every dtype, so the cast is lossless)."""
    if mask is not None:
        logits = logits + (1.0 - mask.astype(logits.dtype)) * NEG_INF
    return jax.nn.softmax(logits, axis=axis)


def cross_entropy(logits: jax.Array, labels: jax.Array,
                  label_smoothing: float = 0.0) -> jax.Array:
    """Per-position CE with Marian's label smoothing (reference:
    gpu::CrossEntropyPick + layers/loss.cpp):
      ce = (1-eps) * -logP(label) - eps * mean_v logP(v)
    computed in f32 regardless of logit dtype."""
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32),
                               axis=-1)[..., 0]
    if label_smoothing > 0.0:
        smooth = -jnp.mean(logp, axis=-1)
        nll = (1.0 - label_smoothing) * nll + label_smoothing * smooth
    return nll


def global_norm(tree) -> jax.Array:
    """L2 norm over a pytree of grads (reference: clippers.cpp norm over the
    flat gradient arena)."""
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves))


def clip_by_global_norm(tree, max_norm: float, norm: Optional[jax.Array] = None):
    if max_norm <= 0:
        return tree
    if norm is None:
        norm = global_norm(tree)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-8))
    return jax.tree_util.tree_map(lambda g: (g * scale).astype(g.dtype), tree)
