"""Pallas TPU kernels for ops XLA does not fuse well enough on its own.

The reference's equivalent layer is the hand-written CUDA kernel zoo in
src/tensors/gpu/ (element.cu, tensor_operators.cu, prod.cpp). Here almost
all of that collapses into XLA fusion; the kernels that remain are the ones
where *blockwise scheduling across the memory hierarchy* (HBM->VMEM) is the
win: flash attention for long sequences, head-packed attention for the
short-sequence MXU-tile-geometry regime, and the fused beam-gather +
cache-read decode step.
"""

from .decode_attention import decode_attention  # noqa: F401
from .flash_attention import flash_attention  # noqa: F401
from .packed_attention import packed_attention  # noqa: F401
