"""Paged KV-cache pool for iteration-level (continuous) batching.

The dense decode cache is a per-batch tensor ``[rows, H, L, dh]`` whose
row count and length are fixed for the LIFETIME of the batch: a sentence
admitted mid-decode waits for the whole batch to drain, and every row
pays L positions of HBM even when it finished at position 9. This module
replaces it with a POOL of fixed-size pages:

- ``pool_k`` / ``pool_v``: ``[n_pages, H, page_len, dh]`` — one shared
  allocation sized to a byte budget, not to any batch;
- a per-row PAGE TABLE ``[rows, max_pages]`` int32 mapping each row's
  logical positions ``[j*page_len, (j+1)*page_len)`` to a physical page;
- per-row positions ``row_pos`` int32 — rows decode at their OWN time
  index, so a sentence can join a running decode step at position 0
  while its neighbors are at position 40.

Page 0 is RESERVED as the trash page: it is never handed out by the
allocator, table entries of unclaimed slots point at it, and inactive
rows (``row_pos < 0``) write zeros into it — so scatter collisions
between idle rows write identical values and stay deterministic (the
join/evict replay test pins this).

``paged_decode_attention`` extends the fused decode kernel's
scalar-prefetch index map (ops/pallas/decode_attention.py) from beam
backpointers to page-table lookups: grid cell ``(row, head, page)``
pulls physical page ``page_table[row, page]`` through the block index
map, accumulates the row's K/V pages into VMEM scratch, and on the last
page runs EXACTLY the dense kernel's one-shot masked softmax over the
assembled ``[max_pages*page_len, dh]`` block — the op order is kept
identical to the dense kernel on purpose, so paged-vs-dense parity is
BITWISE in interpret mode (tests/test_kv_pool.py pins it), not just
allclose.

Update discipline: the dense fused kernel wrote the WHOLE reordered
cache back once per step because the beam reorder demanded it. Here the
reorder is a page-table remap (host-side int32 rows), so the per-step
pool update shrinks to ONE scatter of the new token's K/V into its page
(``pool_insert``) — the kernel reads the pool and writes nothing back.

Shapes stay static for the TPU compilation model: page counts come from
``auto_tuner.KERNEL_BLOCKS``-style capacity tables and active-row
counts round up to ``ROW_BUCKETS`` (the iteration engine slices a
bucket-sized prefix of its slot state per step).
"""

from __future__ import annotations

import bisect
import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .flash_attention import MASK_VALUE, _HAS_PLTPU, _interpret_default

if _HAS_PLTPU:
    from jax.experimental.pallas import tpu as pltpu
else:  # pragma: no cover — CPU-only envs without TPU lowering registration
    pltpu = None


# ---------------------------------------------------------------------------
# static-shape bucket tables (cf. auto_tuner.KERNEL_BLOCKS: shapes must
# come from a small closed set so serving stays on warm jit caches)
# ---------------------------------------------------------------------------

# active-row buckets for the iteration engine's per-step compiled shapes:
# n_active rounds UP to the next entry (one jit specialization per bucket)
ROW_BUCKETS: Tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)

# tokens per page. 16 × dh=64 × 4 B = 4 KiB per (page, head) K block —
# several HBM bursts per block read, small enough that a 10-token
# sentence wastes at most one mostly-empty page (docs/DECODE_ROOFLINE.md
# r7 discusses the trade)
DEFAULT_PAGE_LEN = 16


def pages_for_tokens(n_tokens: int, page_len: int) -> int:
    """Pages a row needs to hold ``n_tokens`` positions."""
    return max(1, -(-int(n_tokens) // max(1, int(page_len))))


def bucket_rows(n: int, buckets: Sequence[int] = ROW_BUCKETS) -> int:
    """Smallest row bucket >= n (the largest bucket caps it)."""
    buckets = sorted(buckets)
    i = bisect.bisect_left(buckets, max(1, int(n)))
    return buckets[min(i, len(buckets) - 1)]


def state_key_groups(state_keys) -> Tuple[Tuple[str, ...], Tuple[str, ...],
                                          Tuple[str, ...]]:
    """Classify a paged decode state's leaves for the per-step closures
    (ONE definition of the contract — translator/iteration.py's engine
    and translator/greedy.py's paged A/B comparator both consume it, so
    a state-layout change cannot silently diverge them):

    - row keys (cross-attention K/V): row-indexed, sliced to the step's
      bucket prefix;
    - pool keys (the paged K/V pools): rewritten by every step;
    - whole keys (beam-invariant extras like LSH tables): pass through.

    ``pos``/``page_table`` are the host-owned leaves and belong to
    neither group.
    """
    keys = tuple(state_keys)
    row_keys = tuple(k for k in keys if "_cross_" in k)
    pool_keys = tuple(k for k in keys if "_pool_" in k)
    whole_keys = tuple(k for k in keys
                       if k not in row_keys and k not in pool_keys
                       and k not in ("pos", "page_table"))
    return row_keys, pool_keys, whole_keys


# ---------------------------------------------------------------------------
# host-side page allocator
# ---------------------------------------------------------------------------

class PoolExhausted(RuntimeError):
    """A claim could not be satisfied — callers must treat this as an
    admission decision (defer/shed the sentence), never as a reason to
    stall a decode step that other rows are waiting on."""


class PoolCorruption(RuntimeError):
    """The pool auditor found an invariant violation (double-freed page,
    page-table/claim mismatch, leaked pages). ``retriable``: the engine
    holding the pool is rebuilt from scratch by the serving scheduler,
    so the evicted rows' requests can be retried against the fresh
    engine — clients see ``!!SERVER-RETRY``, never silent corruption."""

    retriable = True


class KVPool:
    """Refcounted free-list page allocator over the device pool's index
    space.

    Pure host bookkeeping (the device arrays live with the decode state).
    An owner's claim is the list of TABLE REFERENCES its page-table row
    holds; a page's refcount is the number of table references across all
    owners. Fresh claims are all-or-nothing per owner so a greedy
    sentence either holds every page its decode cap needs or none —
    mid-decode exhaustion is impossible by construction for that path,
    which is what keeps the decode step deadlock-free when the pool runs
    dry (admission defers instead).

    Copy-on-write sharing (beam>1 iteration decoding, cross-request
    prefix sharing) rides the refcounts: FULL pages are append-only and
    therefore shareable — :meth:`share` adds references to live pages,
    :meth:`retable` rewrites one owner's reference list as an
    incref/decref diff (the beam reorder), and a page returns to the
    free list only when its LAST reference drops. Only the current
    PARTIAL page of a row is ever written, so it must stay refcount-1
    per row (the engines fork it by content copy — ``pool_fork_partial``).

    Cross-thread: the device worker claims/releases while the metrics
    scrape thread samples the gauges — hence the lock discipline.
    """

    def __init__(self, n_pages: int, page_len: int = DEFAULT_PAGE_LEN,
                 max_pages_per_row: int = 0):
        if n_pages < 2:
            raise ValueError(f"KVPool needs >= 2 pages (page 0 is the "
                             f"reserved trash page); got {n_pages}")
        from ...common import lockdep
        from ...common import ownwit
        # runtime ownership witness (ISSUE 15): with MARIAN_OWNWIT=1
        # every acquire/release/transfer records its acting call site,
        # and tier-1 asserts observed pairings ⊆ the static ownership
        # graph. Read once at construction: one attribute check per
        # verb when disarmed.
        self._ownwit = ownwit.enabled()
        self._ownwit_tok = ownwit.new_token() if self._ownwit else 0
        self.n_pages = int(n_pages)
        self.page_len = int(page_len)
        self.max_pages_per_row = int(max_pages_per_row) or (n_pages - 1)
        self._lock = lockdep.make_lock("KVPool._lock")
        # LIFO free list, low pages first out — keeps early tests and
        # replays deterministic and dense near the pool's base
        self._free: List[int] = list(range(self.n_pages - 1, 0, -1))
        self._claims: Dict[object, List[int]] = {}  # guarded-by: _lock
        # page -> live reference count; a page is EITHER here (>= 1) or
        # on the free list, never both and never absent from both
        self._refs: Dict[int, int] = {}             # guarded-by: _lock
        # cumulative traffic counters (ISSUE 14 pool telemetry):
        #  claimed — fresh pages popped off the free list;
        #  freed   — pages returned to the free list (last ref dropped);
        #  aliased — references added to ALREADY-LIVE pages (the
        #            copy-on-write shares: beam forks, prefix hits,
        #            retable increfs of newly shared pages).
        # The engines read round deltas of these for the serve.round
        # span and the pages_*_total series.
        self._stats = {"claimed": 0, "freed": 0,
                       "aliased": 0}                # guarded-by: _lock

    @property
    def usable_pages(self) -> int:
        """Allocatable pages (total minus the reserved trash page)."""
        return self.n_pages - 1

    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    def used_pages(self) -> int:
        with self._lock:
            return self.n_pages - 1 - len(self._free)

    def refcount(self, page: int) -> int:
        with self._lock:
            return self._refs.get(int(page), 0)

    def refcounts(self) -> Dict[int, int]:
        """Snapshot of the live refcount map (one lock acquisition —
        callers scanning many pages must use this, not per-page
        :meth:`refcount` calls against the device worker's lock)."""
        with self._lock:
            return dict(self._refs)

    def claim(self, owner, n: int, row_cap: bool = True) -> List[int]:
        """Claim ``n`` fresh pages (refcount 1 each) for ``owner``
        (all-or-nothing); raises :class:`PoolExhausted` when the free
        list is short. ``row_cap=False`` skips the per-row table bound —
        for TRANSIENT hold owners that never become a table row (the
        fused beam round's fresh-page pre-claim spans a whole sentence's
        worth of rows, not one)."""
        n = int(n)
        if row_cap and n > self.max_pages_per_row:
            raise PoolExhausted(
                f"row needs {n} pages but the page table holds "
                f"{self.max_pages_per_row} (raise --kv-page-len or the "
                f"pool budget)")
        with self._lock:
            if owner in self._claims:
                raise ValueError(f"owner {owner!r} already holds pages")
            if n > len(self._free):
                raise PoolExhausted(
                    f"pool exhausted: {n} pages requested, "
                    f"{len(self._free)} free of {self.n_pages - 1}")
            pages = [self._free.pop() for _ in range(n)]
            for p in pages:
                self._refs[p] = 1
            self._claims[owner] = pages
            self._stats["claimed"] += n
        if self._ownwit:
            from ...common import ownwit
            ownwit.note_acquire("kv-pages", self._ownwit_tok, owner)
        return list(pages)

    def claim_extra(self, owner, n: int = 1,
                    row_cap: bool = True) -> List[int]:
        """Append ``n`` fresh pages to an EXISTING owner's reference
        list (lazy growth: a beam row crossing a page boundary, a COW
        fork's new partial page). All-or-nothing like :meth:`claim`.
        ``row_cap=False`` skips the per-row table bound — for TRANSIENT
        hold owners that never become a table row (the beam reorder's
        incref-before-decref window)."""
        n = int(n)
        with self._lock:
            held = self._claims.get(owner)
            if held is None:
                raise ValueError(f"owner {owner!r} holds no pages to "
                                 f"extend (use claim)")
            if row_cap and len(held) + n > self.max_pages_per_row:
                raise PoolExhausted(
                    f"row would hold {len(held) + n} pages but the page "
                    f"table holds {self.max_pages_per_row}")
            if n > len(self._free):
                raise PoolExhausted(
                    f"pool exhausted: {n} extra pages requested, "
                    f"{len(self._free)} free of {self.n_pages - 1}")
            pages = [self._free.pop() for _ in range(n)]
            for p in pages:
                self._refs[p] = 1
            held.extend(pages)
            self._stats["claimed"] += n
        if self._ownwit:
            from ...common import ownwit
            ownwit.note_acquire("kv-pages", self._ownwit_tok, owner)
        return list(pages)

    def share(self, owner, pages: Sequence[int],
              row_cap: bool = True) -> None:
        """Add references to LIVE pages for ``owner`` (creating the
        owner if absent): the copy-on-write alias — a beam fork's or a
        prefix-cache hit's table row pointing at another lineage's full
        (append-only, immutable) pages. Refuses dead pages loudly: an
        alias to a freed page would serve recycled KV content.
        ``row_cap=False``: see :meth:`claim_extra`."""
        with self._lock:
            for p in pages:
                p = int(p)
                if self._refs.get(p, 0) < 1:
                    raise ValueError(
                        f"cannot share page {p}: not live (freed or "
                        f"never claimed)")
            held = self._claims.setdefault(owner, [])
            if row_cap and len(held) + len(pages) \
                    > self.max_pages_per_row:
                raise PoolExhausted(
                    f"row would hold {len(held) + len(pages)} pages but "
                    f"the page table holds {self.max_pages_per_row}")
            for p in pages:
                self._refs[int(p)] += 1
                held.append(int(p))
            self._stats["aliased"] += len(pages)
        if self._ownwit:
            from ...common import ownwit
            ownwit.note_acquire("kv-pages", self._ownwit_tok, owner)

    def retable(self, owner, new_pages: Sequence[int]) -> int:
        """Atomically rewrite ``owner``'s reference list to
        ``new_pages`` (the beam reorder's refcount fixup): increfs the
        additions, decrefs the removals, frees pages whose last
        reference dropped. Every page in ``new_pages`` must already be
        live (either kept from the old list or claimed/shared moments
        before). Returns the number of pages FREED. An empty
        ``new_pages`` drops the owner entirely."""
        new_list = [int(p) for p in new_pages]
        with self._lock:
            owner_existed = owner in self._claims
            old_list = self._claims.get(owner, [])
            if len(new_list) > self.max_pages_per_row:
                raise PoolExhausted(
                    f"row would hold {len(new_list)} pages but the page "
                    f"table holds {self.max_pages_per_row}")
            for p in new_list:
                if self._refs.get(p, 0) < 1:
                    raise ValueError(
                        f"cannot retable to page {p}: not live")
            old_set = set(old_list)
            for p in new_list:
                self._refs[p] += 1
                if p not in old_set:
                    # a reference this owner did not already hold: a
                    # genuinely new alias (kept pages incref+decref and
                    # must not read as COW traffic)
                    self._stats["aliased"] += 1
            freed = 0
            # decref the old list in reverse so a retable-to-empty frees
            # in release()'s deterministic order
            for p in reversed(old_list):
                self._refs[p] -= 1
                if self._refs[p] == 0:
                    del self._refs[p]
                    self._free.append(p)
                    freed += 1
            self._stats["freed"] += freed
            if new_list:
                self._claims[owner] = new_list
            else:
                self._claims.pop(owner, None)
        if self._ownwit:
            from ...common import ownwit
            if new_list:
                # kept or created: the retable site holds references now
                ownwit.note_acquire("kv-pages", self._ownwit_tok, owner)
            elif owner_existed:
                # retable-to-empty IS the beam engine's release verb
                ownwit.note_release("kv-pages", self._ownwit_tok, owner)
        return freed

    def transfer(self, src_owner, dst_owner) -> List[int]:
        """Move ``src_owner``'s whole reference list to ``dst_owner``
        (refcounts unchanged — the references change hands, they do not
        multiply): how a finished row's pages become a prefix-cache
        entry without a free/reclaim round trip. Returns the moved
        list; a missing source moves nothing."""
        with self._lock:
            if dst_owner in self._claims:
                raise ValueError(f"transfer target {dst_owner!r} "
                                 f"already holds pages")
            pages = self._claims.pop(src_owner, None)
            if not pages:
                return []
            self._claims[dst_owner] = pages
        if self._ownwit:
            from ...common import ownwit
            ownwit.note_transfer("kv-pages", self._ownwit_tok, src_owner, dst_owner)
        return list(pages)

    def release(self, owner) -> int:
        """Drop every reference ``owner`` holds (freeing pages whose
        last reference drops); returns how many REFERENCES were
        dropped (== pages freed when nothing was shared).

        An owner that holds NOTHING — released twice, or released after
        its references were transferred away (the prefix-cache adoption
        path) — is a loud ``ValueError``, never a silent no-op: a
        double release means some other owner's refcounts are about to
        be wrong, and the caller's bookkeeping has already diverged
        from the pool's (ISSUE 15; MT-OWN-DOUBLE is the static half).
        An owner holding an empty reference list (a zero-page share)
        releases normally."""
        from ...common import faultpoints as fp
        try:
            # the seeded-leak drill (ISSUE 15): an armed 'fail' makes
            # this release silently do NOTHING — the suppressed-release
            # bug class — so the ownership witness's and the auditors'
            # claims to catch a real leak are proven against one
            # (tests/test_ownwit.py; docs/ROBUSTNESS.md "Auditor
            # drills"). Unarmed: one dict lookup.
            fp.fault_point("pool.release_drop")
        except fp.InjectedFault:
            return 0
        with self._lock:
            pages = self._claims.pop(owner, None)
            if pages is None:
                raise ValueError(
                    f"release of owner {owner!r} which holds no pages — "
                    f"released twice, or released after its references "
                    f"were transferred away")
            # freed pages return in reverse so a release+reclaim of the
            # same count yields the same page ids (replay determinism)
            for p in reversed(pages):
                self._refs[p] -= 1
                if self._refs[p] == 0:
                    del self._refs[p]
                    self._free.append(p)
                    self._stats["freed"] += 1
        if self._ownwit:
            from ...common import ownwit
            ownwit.note_release("kv-pages", self._ownwit_tok, owner)
        return len(pages)

    def pages_of(self, owner) -> List[int]:
        with self._lock:
            return list(self._claims.get(owner, []))

    def owners(self) -> List[object]:
        with self._lock:
            return list(self._claims.keys())

    def claims(self) -> Dict[object, List[int]]:
        """Snapshot of the whole claims table (owner -> held page
        references) in one lock acquisition — the /poolz page map
        inverts this into per-page owner lists (ISSUE 14)."""
        with self._lock:
            return {k: list(v) for k, v in self._claims.items()}

    def stats(self) -> Dict[str, int]:
        """Cumulative claimed/freed/aliased counters (see __init__);
        the engines diff two snapshots for per-round accounting."""
        with self._lock:
            return dict(self._stats)

    def alias_stats(self) -> Dict[str, int]:
        """One-lock refcount-distribution summary for the pool gauges:
        ``live`` pages holding references, ``shared`` pages with
        refcount >= 2 (COW-aliased), total ``refs`` and the ``max``
        refcount. The COW alias ratio is (refs - live) / refs — the
        fraction of table references that are aliases rather than sole
        ownership."""
        with self._lock:
            refs = self._refs
            return {
                "live": len(refs),
                "shared": sum(1 for c in refs.values() if c > 1),
                "refs": sum(refs.values()),
                "max": max(refs.values(), default=0),
            }

    # -- invariant auditor (ISSUE 11, refcounts ISSUE 12) -------------------
    def audit(self) -> List[str]:
        """Cross-check the free list, the claims table and the refcount
        map; returns a list of human-readable violations (empty =
        clean). The checks are exactly the bug classes a refcounted
        paged allocator grows over time:

        - a page on the free list twice, or both free and refcounted
          (double-free / freed page with refcount > 0);
        - a claim naming a page out of the pool's index range, or the
          reserved trash page 0 handed out;
        - sum of table references per page != its refcount (a lost or
          phantom incref — the COW fork/reorder bug class);
        - a refcount <= 0 entry lingering in the map (a page with
          refcount 0 may exist ONLY on the free list);
        - pages accounted to neither side (leak).

        Runs on snapshots taken under the lock, so it never blocks the
        device worker for more than three dict copies; callers run it at
        every quiesce boundary and per round under MARIAN_POOL_AUDIT=1.
        """
        with self._lock:
            free = list(self._free)
            claims = {k: list(v) for k, v in self._claims.items()}
            refs = dict(self._refs)
        v: List[str] = []
        seen_free: Dict[int, bool] = {}
        for p in free:
            if p == 0:
                v.append("free list holds the reserved trash page 0")
                continue
            if not 1 <= p < self.n_pages:
                v.append(f"free list holds out-of-range page {p}")
                continue
            if p in seen_free:
                v.append(f"page {p} appears twice in the free list "
                         f"(double-free)")
            seen_free[p] = True
            if refs.get(p, 0) > 0:
                v.append(f"page {p} is free but still has refcount "
                         f"{refs[p]} (freed page with live references)")
        # rebuild the expected refcounts from the claims table
        expected: Dict[int, int] = {}
        for owner, pages in claims.items():
            for p in pages:
                if p == 0 or not 1 <= p < self.n_pages:
                    v.append(f"claim {owner!r} holds invalid page {p}")
                    continue
                expected[p] = expected.get(p, 0) + 1
        for p, want in sorted(expected.items()):
            have = refs.get(p, 0)
            if have != want:
                v.append(f"page {p} has refcount {have} but "
                         f"{want} table reference(s) (refcount drift)")
            if p in seen_free:
                v.append(f"page {p} is both free and referenced "
                         f"(double-free)")
        for p, rc in sorted(refs.items()):
            if rc <= 0:
                v.append(f"page {p} has non-positive refcount {rc} "
                         f"outside the free list")
            elif p not in expected:
                v.append(f"page {p} has refcount {rc} but no table "
                         f"reference names it (phantom refcount)")
        if not v:
            total = len(free) + len(refs)
            if total != self.usable_pages:
                v.append(f"{self.usable_pages - total} page(s) leaked: "
                         f"{len(free)} free + {len(refs)} live of "
                         f"{self.usable_pages} allocatable")
        return v

    def chaos_double_free(self) -> None:
        """Cross the ``pool.double_free`` detection drill. The catalog
        point's 'fail' mode does not model an exception here: it makes
        this helper re-free one still-claimed row's pages — the real
        double-free state — so the auditor's claim to catch that bug
        class is tested against actual corruption, never a mocked
        report (docs/ROBUSTNESS.md "Auditor drills"). Unarmed, this is
        one dict lookup under the faultpoint lock; kill/hang modes
        behave as at any other crossing."""
        from ...common import faultpoints as fp
        try:
            fp.fault_point("pool.double_free")
        except fp.InjectedFault:
            with self._lock:
                for pages in self._claims.values():
                    if pages:
                        self._free.extend(reversed(pages))
                        break

    def chaos_refcount_corrupt(self) -> None:
        """Cross the ``pool.refcount_corrupt`` detection drill: an armed
        'fail' bumps one live page's refcount by +1 WITHOUT adding a
        table reference — the lost-decref/phantom-incref bug class the
        COW fork/reorder paths could grow — so the auditor's
        references-vs-refcount cross-check is proven against real
        corrupted state (docs/ROBUSTNESS.md "Auditor drills")."""
        from ...common import faultpoints as fp
        try:
            fp.fault_point("pool.refcount_corrupt")
        except fp.InjectedFault:
            with self._lock:
                for p in sorted(self._refs):
                    self._refs[p] += 1
                    break

    def chaos_tenant_leak(self) -> None:
        """Cross the ``tenant.page_leak`` detection drill (ISSUE 20): an
        armed 'fail' moves ONE page reference from some tenant's claim
        list into a claim list owned by a DIFFERENT tenant — the
        mischarged-page bug class of multi-tenant accounting. The move
        changes no refcount, so :meth:`audit` stays green BY
        CONSTRUCTION; only the tenant-level auditor
        (serving/fleet/accounting.py::audit_tenants) can catch it, which
        is exactly what the drill proves. No-op (beyond the faultpoint
        crossing) when the pool holds claims from fewer than two
        distinct tenants."""
        from ...common import faultpoints as fp
        try:
            fp.fault_point("tenant.page_leak")
        except fp.InjectedFault:
            from ...serving.fleet import accounting as acc  # lazy: leaf
            with self._lock:
                by_tenant = {}
                for owner, pages in self._claims.items():
                    t = acc.tenant_of_owner(owner)
                    if t:
                        by_tenant.setdefault(t, []).append(owner)
                tenants = sorted(by_tenant)
                for src_t in tenants:
                    src = next((o for o in by_tenant[src_t]
                                if self._claims[o]), None)
                    dst_t = next((t for t in tenants if t != src_t), None)
                    if src is None or dst_t is None:
                        continue
                    dst = by_tenant[dst_t][0]
                    self._claims[dst].append(self._claims[src].pop())
                    return


# ---------------------------------------------------------------------------
# device-side pool ops
# ---------------------------------------------------------------------------

def pool_insert(pool_k: jax.Array, pool_v: jax.Array,
                k_new: jax.Array, v_new: jax.Array,
                page_table: jax.Array, row_pos: jax.Array
                ) -> Tuple[jax.Array, jax.Array]:
    """Write each active row's new-token K/V into its page at
    ``row_pos`` — the paged pool's ONE write per step (the dense fused
    kernel's full write-back existed only to apply the beam reorder; the
    page table absorbs that, so only the new token moves).

    ``row_pos < 0`` marks an inactive row: its write is redirected to
    the trash page (0) offset 0 with a ZERO payload, so idle-row scatter
    collisions write identical values and the result is deterministic.
    """
    page_len = pool_k.shape[2]
    mp = page_table.shape[1]
    pos = jnp.asarray(row_pos, jnp.int32)
    active = pos >= 0
    # clamp into the table's span: a multi-step scan round can step a
    # row past its cap before the host sees the EOS and evicts it — the
    # overshoot lands on the row's own last slot (a position the host
    # has already cut at), never out of bounds
    posc = jnp.where(active, jnp.minimum(pos, mp * page_len - 1), 0)
    slot = posc // page_len                                   # [R]
    pidx = jnp.take_along_axis(jnp.asarray(page_table, jnp.int32),
                               slot[:, None], axis=1)[:, 0]   # [R]
    pidx = jnp.where(active, pidx, 0)
    off = jnp.where(active, posc % page_len, 0)
    kv = []
    for pool, new in ((pool_k, k_new), (pool_v, v_new)):
        payload = new[:, :, 0, :].astype(pool.dtype)          # [R,H,dh]
        payload = jnp.where(active[:, None, None], payload,
                            jnp.zeros_like(payload))
        kv.append(pool.at[pidx, :, off, :].set(payload))
    return kv[0], kv[1]


def pool_fork_partial(pool_k: jax.Array, pool_v: jax.Array,
                      src_pages: jax.Array, dst_pages: jax.Array
                      ) -> Tuple[jax.Array, jax.Array]:
    """Copy-on-write fork of PARTIAL pages: ``pool[dst] = pool[src]``
    for each (src, dst) pair — the one content copy a beam reorder (or
    a cross-request prefix fork) pays per diverging row, H·page_len·dh
    elements against the dense path's full H·L·dh reorder.

    Pairs with ``src == dst == 0`` are padding (they rewrite the trash
    page with its own content — deterministic no-ops), so callers can
    bucket the pair count to a static shape. Duplicate destinations are
    only ever the padded zeros, whose payloads are identical, so the
    scatter stays deterministic."""
    src = jnp.asarray(src_pages, jnp.int32)
    dst = jnp.asarray(dst_pages, jnp.int32)
    new_k = pool_k.at[dst].set(pool_k[src])
    new_v = pool_v.at[dst].set(pool_v[src])
    return new_k, new_v


def beam_table_reorder(page_table: jax.Array, parent: jax.Array,
                       write_slot: jax.Array, fresh_page: jax.Array,
                       needs_fresh: jax.Array, frozen: jax.Array
                       ) -> jax.Array:
    """The beam reorder's page-table half, as int32 table math: each
    surviving row inherits its ``parent`` row's table, and the rows
    that diverge (``needs_fresh`` — a page-boundary crossing or a
    non-keeper child that must fork the partial page) get their
    ``write_slot`` entry repointed at a host-claimed ``fresh_page``.
    ``frozen`` rows (EOS'd hypotheses carried for the merge) zero their
    table — they stop writing and hold no pages.

    Pure table→table function so the multi-step beam scan can carry it;
    refcounts stay a HOST concern: the engine applies the resulting
    table as a ``retable`` diff after the round syncs."""
    t = jnp.asarray(page_table, jnp.int32)
    new = t[jnp.asarray(parent, jnp.int32)]
    hot = (jnp.arange(t.shape[1], dtype=jnp.int32)[None, :]
           == jnp.asarray(write_slot, jnp.int32)[:, None])
    new = jnp.where(hot & jnp.asarray(needs_fresh)[:, None],
                    jnp.asarray(fresh_page, jnp.int32)[:, None], new)
    return jnp.where(jnp.asarray(frozen)[:, None], 0, new)


def _reference(q, pool_k, pool_v, page_table, row_pos, scale):
    """Pure-jnp paged attention read (backends without pltpu, or rows
    past the VMEM token cap). Gathers each row's pages and then runs the
    EXACT op sequence of the dense reference (decode_attention._reference)
    over the assembled [R, H, MP*PL, dh] view — elementwise-identical
    inputs at unmasked positions + identical ops = bitwise-identical
    outputs vs a dense cache of length MP*PL (tests pin this)."""
    r, mp = page_table.shape
    page_len = pool_k.shape[2]
    h, dh = pool_k.shape[1], pool_k.shape[3]

    def gather(pool):
        g = pool[page_table]                          # [R, MP, H, PL, dh]
        return g.transpose(0, 2, 1, 3, 4).reshape(r, h, mp * page_len, dh)

    k_full, v_full = gather(pool_k), gather(pool_v)
    s = jnp.einsum("rhqd,rhkd->rhqk", q.astype(jnp.float32),
                   k_full.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    steps = jnp.arange(mp * page_len)[None, None, None, :]
    s = jnp.where(steps <= row_pos[:, None, None, None], s, MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("rhqk,rhkd->rhqd", p, v_full.astype(jnp.float32),
                      preferred_element_type=jnp.float32).astype(q.dtype)


def _kernel(pt_ref, pos_ref, q_ref, pk_ref, pv_ref, o_ref, ks_ref, vs_ref,
            *, scale, page_len, n_pages_row):
    """Grid (R, H, MP): cells p = 0..MP-1 stage the row's pages into
    VMEM scratch (the physical page arrived via the scalar-prefetch
    block index map); the LAST cell runs the dense kernel's one-shot
    masked softmax over the assembled row — op order kept identical to
    decode_attention._kernel so parity is bitwise in interpret mode."""
    # program ids hoisted to the top level: the interpret-mode lowering
    # only rewrites program_id in the kernel's own trace, not inside a
    # pl.when branch (same hoist the flash kernels do)
    r = pl.program_id(0)
    p = pl.program_id(2)
    ks_ref[pl.ds(p * page_len, page_len), :] = pk_ref[0, 0]
    vs_ref[pl.ds(p * page_len, page_len), :] = pv_ref[0, 0]

    @pl.when(p == n_pages_row - 1)
    def _finish():
        pos = pos_ref[r]
        max_len = n_pages_row * page_len
        qv = q_ref[0, 0].astype(jnp.float32)              # [1, dh]
        s = jax.lax.dot_general(
            qv, ks_ref[:].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [1, L]
        steps = jax.lax.broadcasted_iota(jnp.int32, (1, max_len), 1)
        s = jnp.where(steps <= pos, s, MASK_VALUE)
        m = jnp.max(s, axis=1, keepdims=True)
        pr = jnp.exp(s - m)
        pr = pr / jnp.sum(pr, axis=1, keepdims=True)
        o = jax.lax.dot_general(
            pr, vs_ref[:].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [1, dh]
        o_ref[0, 0] = o.astype(o_ref.dtype)


def paged_decode_attention(q: jax.Array, k_new: jax.Array, v_new: jax.Array,
                           pool_k: jax.Array, pool_v: jax.Array,
                           page_table: jax.Array, row_pos: jax.Array,
                           scale: Optional[float] = None,
                           interpret: Optional[bool] = None
                           ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One paged decode-attention step; see module docstring.

    q/k_new/v_new ``[R, H, 1, dh]``; pool_k/pool_v
    ``[n_pages, H, page_len, dh]``; page_table ``[R, max_pages]`` int32;
    row_pos ``[R]`` int32 per-row write positions (< 0 = inactive row —
    no pool write, deterministic-garbage output the caller masks).
    Returns ``(context [R,H,1,dh], new_pool_k, new_pool_v)`` — the new
    pools hold the inserted tokens (ONE scatter; no full write-back).
    """
    r, h, _, dh = q.shape
    mp = page_table.shape[1]
    page_len = pool_k.shape[2]
    if scale is None:
        scale = 1.0 / (dh ** 0.5)
    row_pos = jnp.asarray(row_pos, jnp.int32)
    page_table = jnp.asarray(page_table, jnp.int32)

    new_k, new_v = pool_insert(pool_k, pool_v, k_new, v_new,
                               page_table, row_pos)

    from ..auto_tuner import kv_pool_max_tokens
    if interpret is None:
        # default gate mirrors the fused decode kernel's 'auto': the
        # kernel only pays on the TPU backend — interpret mode
        # emulates every (row, head, page) grid cell sequentially
        # (seconds per step at serving widths), and the jnp gather
        # reference is BITWISE-identical anyway (tests pin it; tests
        # pass interpret=True explicitly to exercise the kernel)
        interpret = _interpret_default()
        if interpret:
            out = _reference(q, new_k, new_v, page_table, row_pos,
                             float(scale))
            return out, new_k, new_v
    if not _HAS_PLTPU or mp * page_len > kv_pool_max_tokens(dh):
        # degrade, don't OOM: the scratch row [MP*PL, dh] x2 must fit
        # the VMEM budget (auto_tuner scales the cap down for wide heads)
        out = _reference(q, new_k, new_v, page_table, row_pos,
                         float(scale))
        return out, new_k, new_v

    kernel = functools.partial(_kernel, scale=float(scale),
                               page_len=page_len, n_pages_row=mp)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(r, h, mp),
        in_specs=[
            pl.BlockSpec((1, 1, 1, dh), lambda r_, h_, p_, t, s: (r_, h_, 0, 0)),
            # the page-table gather: pool blocks come from the PHYSICAL
            # page the row's table names for logical page p
            pl.BlockSpec((1, 1, page_len, dh),
                         lambda r_, h_, p_, t, s: (t[r_, p_], h_, 0, 0)),
            pl.BlockSpec((1, 1, page_len, dh),
                         lambda r_, h_, p_, t, s: (t[r_, p_], h_, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, 1, dh), lambda r_, h_, p_, t, s: (r_, h_, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((mp * page_len, dh), pool_k.dtype),
            pltpu.VMEM((mp * page_len, dh), pool_v.dtype),
        ],
    )
    out, = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((r, h, 1, dh), q.dtype)],
        interpret=bool(interpret),
    )(page_table, row_pos, q, new_k, new_v)
    return out, new_k, new_v
