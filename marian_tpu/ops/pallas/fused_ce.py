"""Fused (streaming) softmax cross-entropy over the output vocabulary as
Pallas TPU kernels — the training-side counterpart of flash attention.

The reference materializes [B,T,V] logits and a second [B,T,V] log-softmax
(src/tensors/gpu/tensor_operators.cu :: LogSoftmax + CrossEntropyPick); at
V=32k and memory-filling batches those two f32 tensors (and their gradients)
dominate HBM traffic — the round-1 profile showed the logits/CE chain as the
largest per-token cost of the train step. This module computes the output
projection and the label-smoothed CE in one pass: vocab blocks of the logits
matmul are formed in VMEM, reduced online (running max / sum-exp / label
gather / logit sum), and never written to HBM. The backward recomputes logits
blockwise (two passes: d-hidden, then d-table/d-bias) exactly like the flash
attention backward.

The VJP boundary is the per-token stats triple

    lse_i = logsumexp_v(logits_iv)      (running max + sum-exp)
    lab_i = logits_i[label_i]           (label logit)
    tot_i = sum_v logits_iv             (for the label-smoothing mean)

from which the caller composes Marian's smoothed CE
    ce_i = (1-eps) * (lse_i - lab_i) + eps * (lse_i - tot_i / V)
in plain (cheap, [N]-shaped) jnp; d logits = g_lse * softmax
+ g_lab * onehot + g_tot is formed blockwise in the backward kernels.

Shapes: x [N, E] hidden states, w [V, E] output table (tied embedding
orientation; logits = x @ w.T + b), b [V], labels [N]. Compute is f32 on the
MXU regardless of input dtype (bf16 in training), matching the dense path's
`preferred_element_type=float32` discipline.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # same fallback as flash_attention.py (CPU-only test processes)
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # noqa: BLE001
    pltpu = None
    _HAS_PLTPU = False

MASK_VALUE = -1e9       # bias for padded vocab rows: exp() == 0 in f32
STATS_INIT = -1e30
_LANES = 128


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _vmem(shape, dtype):
    if _HAS_PLTPU:
        return pltpu.VMEM(shape, dtype)
    return pl.MemoryRef(shape, dtype)  # pragma: no cover


def _compiler_params():
    if not _HAS_PLTPU:  # pragma: no cover
        return None
    # Large-ish blocks (the vocab table is re-streamed once per token block,
    # so bigger token blocks cut HBM traffic) need more than the default
    # 16MB scoped-VMEM allowance; v5e/v4 have 128MB physical VMEM.
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "arbitrary"),
        vmem_limit_bytes=100 * 1024 * 1024)


# ---------------------------------------------------------------------------
# Forward: grid (n_n, n_v); the vocab axis is innermost and sequential, so
# the running stats live in VMEM scratch across vocab blocks.
# ---------------------------------------------------------------------------

def _fwd_kernel(x_ref, w_ref, b_ref, lab_ref, lse_ref, labl_ref, tot_ref,
                m_scr, s_scr, g_scr, t_scr, *, block_v, n_v, v_real):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, STATS_INIT)
        s_scr[:] = jnp.zeros_like(s_scr)
        g_scr[:] = jnp.zeros_like(g_scr)
        t_scr[:] = jnp.zeros_like(t_scr)

    x = x_ref[...]                                     # [bn, E] native dtype
    w = w_ref[...]                                     # [bv, E]
    logits = jax.lax.dot_general(
        x, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)            # [bn, bv] f32 accum
    logits = logits + b_ref[...].astype(jnp.float32)

    bn, bv = logits.shape
    cols = j * block_v + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    valid = cols < v_real                               # padded vocab rows
    logits = jnp.where(valid, logits, MASK_VALUE)

    m_prev = m_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    s_scr[:] = jnp.broadcast_to(
        alpha * s_scr[:, :1]
        + jnp.sum(jnp.exp(logits - m_new), axis=1, keepdims=True),
        s_scr.shape)
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    labels = lab_ref[...][:, :1]                       # [bn, 1] int32
    onehot = (cols == labels).astype(jnp.float32)
    g_scr[:] = g_scr[:] + jnp.broadcast_to(
        jnp.sum(logits * onehot, axis=1, keepdims=True), g_scr.shape)
    t_scr[:] = t_scr[:] + jnp.broadcast_to(
        jnp.sum(jnp.where(valid, logits, 0.0), axis=1, keepdims=True),
        t_scr.shape)

    @pl.when(j == n_v - 1)
    def _finalize():
        s = s_scr[:, :1]
        s_safe = jnp.where(s == 0.0, 1.0, s)
        lse_ref[...] = m_scr[:, :1] + jnp.log(s_safe)
        labl_ref[...] = g_scr[:, :1]
        tot_ref[...] = t_scr[:, :1]


# ---------------------------------------------------------------------------
# Backward. d logits_ij = g_lse_i * P_ij + g_lab_i * onehot_ij + g_tot_i
# with P_ij = exp(logits_ij - lse_i); logits are recomputed blockwise.
# Two passes with opposite grid nesting (cf. flash attention backward):
#   dx     : grid (n_n, n_v), accumulate over vocab blocks
#   dw, db : grid (n_v, n_n), accumulate over token blocks
# ---------------------------------------------------------------------------

def _dlogits(x, w, b, labels, lse, g_lse, g_lab, g_tot, j, block_v, v_real):
    logits = jax.lax.dot_general(
        x, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    logits = logits + b[None, :]  # b [bv]
    bn, bv = logits.shape
    cols = j * block_v + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    valid = cols < v_real
    p = jnp.exp(jnp.where(valid, logits, MASK_VALUE) - lse)
    onehot = (cols == labels).astype(jnp.float32)
    d = g_lse * p + g_lab * onehot + jnp.where(valid, g_tot, 0.0)
    return d                                            # [bn, bv] f32


def _dx_kernel(x_ref, w_ref, b_ref, lab_ref, lse_ref, gl_ref, gg_ref, gt_ref,
               dx_ref, dx_scr, *, block_v, n_v, v_real):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        dx_scr[:] = jnp.zeros_like(dx_scr)

    x = x_ref[...]
    w = w_ref[...]
    d = _dlogits(x, w, b_ref[...].astype(jnp.float32)[0],
                 lab_ref[...][:, :1], lse_ref[...][:, :1],
                 gl_ref[...][:, :1], gg_ref[...][:, :1], gt_ref[...][:, :1],
                 j, block_v, v_real)
    dx_scr[:] = dx_scr[:] + jax.lax.dot_general(
        d.astype(w.dtype), w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)             # [bn, E]

    @pl.when(j == n_v - 1)
    def _finalize():
        dx_ref[...] = dx_scr[:].astype(dx_ref.dtype)


def _dw_kernel(x_ref, w_ref, b_ref, lab_ref, lse_ref, gl_ref, gg_ref, gt_ref,
               dw_ref, db_ref, dw_scr, db_scr, *, block_v, n_n, v_real):
    # grid (n_v, n_n): program_id(0) is the vocab block, (1) the token block.
    j, i = pl.program_id(0), pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        dw_scr[:] = jnp.zeros_like(dw_scr)
        db_scr[:] = jnp.zeros_like(db_scr)

    x = x_ref[...]
    w = w_ref[...]
    d = _dlogits(x, w, b_ref[...].astype(jnp.float32)[0],
                 lab_ref[...][:, :1], lse_ref[...][:, :1],
                 gl_ref[...][:, :1], gg_ref[...][:, :1], gt_ref[...][:, :1],
                 j, block_v, v_real)
    dw_scr[:] = dw_scr[:] + jax.lax.dot_general(
        d.astype(x.dtype), x, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)             # [bv, E]
    db_scr[:] = db_scr[:] + jnp.broadcast_to(
        jnp.sum(d, axis=0)[:, None], db_scr.shape)      # [bv, LANES]

    @pl.when(i == n_n - 1)
    def _finalize():
        dw_ref[...] = dw_scr[:].astype(dw_ref.dtype)
        db_ref[...] = db_scr[:, :1].astype(db_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing over padded [N, E] / [V, E]
# ---------------------------------------------------------------------------

def _fwd_call(x, w, b, labels, block_n, block_v, v_real, interpret):
    n, e = x.shape
    v = w.shape[0]
    n_n, n_v = n // block_n, v // block_v
    kernel = functools.partial(_fwd_kernel, block_v=block_v, n_v=n_v,
                               v_real=v_real)
    return pl.pallas_call(
        kernel,
        grid=(n_n, n_v),
        in_specs=[
            pl.BlockSpec((block_n, e), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, e), lambda i, j: (j, 0)),
            pl.BlockSpec((1, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((n, 1), jnp.float32)] * 3,
        scratch_shapes=[_vmem((block_n, _LANES), jnp.float32)
                        for _ in range(4)],
        interpret=interpret,
        compiler_params=None if interpret else _compiler_params(),
    )(x, w, b, labels)


def _bwd_call(x, w, b, labels, lse, g_lse, g_lab, g_tot,
              block_n, block_v, v_real, interpret):
    n, e = x.shape
    v = w.shape[0]
    n_n, n_v = n // block_n, v // block_v

    tok = lambda i, j: (i, 0)        # noqa: E731
    voc = lambda i, j: (j, 0)        # noqa: E731
    in_specs = [
        pl.BlockSpec((block_n, e), tok),
        pl.BlockSpec((block_v, e), voc),
        pl.BlockSpec((1, block_v), lambda i, j: (0, j)),
        pl.BlockSpec((block_n, 1), tok),
        pl.BlockSpec((block_n, 1), tok),
        pl.BlockSpec((block_n, 1), tok),
        pl.BlockSpec((block_n, 1), tok),
        pl.BlockSpec((block_n, 1), tok),
    ]
    dx = pl.pallas_call(
        functools.partial(_dx_kernel, block_v=block_v, n_v=n_v,
                          v_real=v_real),
        grid=(n_n, n_v),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_n, e), tok),
        out_shape=jax.ShapeDtypeStruct((n, e), x.dtype),
        scratch_shapes=[_vmem((block_n, e), jnp.float32)],
        interpret=interpret,
        compiler_params=None if interpret else _compiler_params(),
    )(x, w, b, labels, lse, g_lse, g_lab, g_tot)

    # token/vocab block roles swap in the index maps for the second pass
    tok2 = lambda j, i: (i, 0)       # noqa: E731
    voc2 = lambda j, i: (j, 0)       # noqa: E731
    in_specs2 = [
        pl.BlockSpec((block_n, e), tok2),
        pl.BlockSpec((block_v, e), voc2),
        pl.BlockSpec((1, block_v), lambda j, i: (0, j)),
        pl.BlockSpec((block_n, 1), tok2),
        pl.BlockSpec((block_n, 1), tok2),
        pl.BlockSpec((block_n, 1), tok2),
        pl.BlockSpec((block_n, 1), tok2),
        pl.BlockSpec((block_n, 1), tok2),
    ]
    dw, db = pl.pallas_call(
        functools.partial(_dw_kernel, block_v=block_v, n_n=n_n,
                          v_real=v_real),
        grid=(n_v, n_n),
        in_specs=in_specs2,
        out_specs=[
            pl.BlockSpec((block_v, e), voc2),
            pl.BlockSpec((block_v, 1), lambda j, i: (j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((v, e), w.dtype),
            jax.ShapeDtypeStruct((v, 1), jnp.float32),
        ],
        scratch_shapes=[_vmem((block_v, e), jnp.float32),
                        _vmem((block_v, _LANES), jnp.float32)],
        interpret=interpret,
        compiler_params=None if interpret else _compiler_params(),
    )(x, w, b, labels, lse, g_lse, g_lab, g_tot)
    return dx, dw, db


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _stats(x, w, b, labels, block_n, block_v, v_real, interpret):
    lse, lab, tot = _fwd_call(x, w, b, labels, block_n, block_v, v_real,
                              interpret)
    return lse[:, 0], lab[:, 0], tot[:, 0]


def _stats_fwd(x, w, b, labels, block_n, block_v, v_real, interpret):
    lse, lab, tot = _fwd_call(x, w, b, labels, block_n, block_v, v_real,
                              interpret)
    return (lse[:, 0], lab[:, 0], tot[:, 0]), (x, w, b, labels, lse)


def _stats_bwd(block_n, block_v, v_real, interpret, res, gs):
    x, w, b, labels, lse = res
    g_lse, g_lab, g_tot = (g[:, None] for g in gs)
    dx, dw, db = _bwd_call(x, w, b, labels, lse, g_lse, g_lab, g_tot,
                           block_n, block_v, v_real, interpret)
    return dx, dw, db[:, 0][None, :].astype(b.dtype), None


_stats.defvjp(_stats_fwd, _stats_bwd)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

def _pick_block_v(v: int, cap: int = 2048) -> Optional[int]:
    """Largest multiple of the lane width that divides v (no padding), else
    None (caller pads). 32000 → 1280; 32768 → 2048; 256 → 256."""
    best = None
    for bv in range(_LANES, cap + 1, _LANES):
        if v % bv == 0:
            best = bv
    return best


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def fused_available(e: int, interpret: Optional[bool] = None) -> bool:
    """Compiled-mode kernels need a lane-aligned hidden dim; interpret mode
    (CPU tests) takes anything."""
    if interpret is None:
        interpret = _interpret_default()
    return interpret or (e % _LANES == 0)


def fused_softmax_xent(x: jax.Array, w: jax.Array, b: jax.Array,
                       labels: jax.Array,
                       label_smoothing: float = 0.0,
                       block_n: int = 1024, block_v: int = 2048,
                       interpret: Optional[bool] = None) -> jax.Array:
    """Per-token label-smoothed CE of logits = x @ w.T + b, streaming over
    vocab blocks (never materializing [N, V]).

    x [N, E] (any float dtype; matmuls accumulate f32), w [V, E], b [V],
    labels [N] int → ce [N] f32:
        ce = (1-eps) * (lse - logits[label]) + eps * (lse - mean_v logits)
    which equals ops.cross_entropy(logits, labels, eps) exactly (same
    algebra: -logP(y) = lse - logit_y; -mean_v logP(v) = lse - mean_v logit_v).

    Gradients flow to x, w, b via blockwise-recomputing backward kernels.
    """
    n, e = x.shape
    v = w.shape[0]
    if interpret is None:
        interpret = _interpret_default()

    bv = _pick_block_v(v, block_v)
    if bv is None:
        v_pad = _round_up(v, block_v)
        w = jnp.pad(w, ((0, v_pad - v), (0, 0)))
        b = jnp.pad(b, (0, v_pad - v), constant_values=MASK_VALUE)
        bv = block_v
    bn = min(block_n, _round_up(n, _LANES))
    n_pad = _round_up(n, bn)
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0)))
        labels = jnp.pad(labels, (0, n_pad - n))

    labels2 = labels.astype(jnp.int32)[:, None]
    b2 = b.reshape(1, -1).astype(jnp.float32)
    lse, lab, tot = _stats(x, w, b2, labels2, bn, bv, v, bool(interpret))

    eps = float(label_smoothing)
    nll = lse - lab
    if eps > 0.0:
        ce = (1.0 - eps) * nll + eps * (lse - tot / float(v))
    else:
        ce = nll
    return ce[:n]
