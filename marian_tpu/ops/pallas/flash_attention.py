"""Blockwise (flash) attention as Pallas TPU kernels.

The reference computes attention as two strided-batched cuBLAS GEMMs with a
materialized [B,H,Tq,Tk] score tensor in between (src/tensors/gpu/prod.cpp ::
ProdBatched + gpu::Softmax); fine for NMT sentence lengths, but the O(L^2)
score tensor becomes the HBM-bandwidth bottleneck for doc-level contexts.
This module computes the same masked softmax(QK^T)V with the online-softmax
recurrence, streaming K/V blocks through VMEM so the score matrix never
touches HBM, with a matching blockwise backward (custom VJP).

Supported masking covers every attention pattern in the model zoo:
  - kv_mask [B, Tk]: key padding mask (1.0 = attend), and/or
  - causal: future mask (query position >= key position).
Attention-weight dropout and returned weights are NOT supported here; the
dispatcher (ops/attention.py :: attention) falls back to the dense path for
those cases.

Shapes: q [B, H, Tq, Dh], k/v [B, H, Tk, Dh] -> out [B, H, Tq, Dh].
Compute is f32 on the MXU regardless of input dtype (bf16 in training).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    # On CPU-only processes (tests force jax_platforms=cpu and drop the TPU
    # backend factory) this import can fail while registering TPU lowerings;
    # the interpret path below works without it.
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # noqa: BLE001 — ImportError or NotImplementedError
    pltpu = None
    _HAS_PLTPU = False

MASK_VALUE = -1e9       # additive bias for masked scores (matches ops.NEG_INF)
STATS_INIT = -1e30      # running-max init; NOT -inf so exp() stays finite
_LANES = 128            # TPU lane width; running stats are lane-replicated


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _env_block(name: str, default: int) -> int:
    """Parse a MARIAN_FLASH_BLOCK_* sweep override: positive int, or the
    default with a warning on anything malformed."""
    import os as _os
    raw = _os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        v = int(raw)
        if v <= 0:
            raise ValueError("must be positive")
    except ValueError:
        from ...common import logging as log
        log.warn("{}={!r} is not a positive integer — using the default "
                 "block size {}", name, raw, default)
        return default
    return v


def _vmem(shape, dtype):
    if _HAS_PLTPU:
        return pltpu.VMEM(shape, dtype)
    return pl.MemoryRef(shape, dtype)  # pragma: no cover


# ---------------------------------------------------------------------------
# Forward kernel: grid (B, H, nq, nk); the k-block axis is innermost and
# sequential on TPU, so running stats live in VMEM scratch across k-blocks.
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, kvm_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_k,
                n_k):
    i, j = pl.program_id(2), pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, STATS_INIT)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: skip k-blocks that are entirely in the future of this q-block.
    live = (j * block_k <= i * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)          # [bq, dh]
        k = k_ref[0, 0].astype(jnp.float32)          # [bk, dh]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [bq, bk]
        kvm = kvm_ref[0, 0].astype(jnp.float32)      # [bk]
        s = s + (1.0 - kvm)[None, :] * MASK_VALUE
        if causal:
            qpos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, MASK_VALUE)

        m_prev = m_scr[:, :1]                        # [bq, 1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                       # [bq, bk]
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)          # [bk, dh]
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # [bq, dh]
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == n_k - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)         # fully-masked rows
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = m_scr[:, :1] + jnp.log(l_safe)


# ---------------------------------------------------------------------------
# Backward kernels. Standard flash backward split in two passes:
#   dq : grid (B, H, nq, nk), accumulate over k-blocks
#   dkv: grid (B, H, nk, nq), accumulate over q-blocks
# p is recomputed from (q, k, lse); delta = rowsum(do * o) is precomputed.
# ---------------------------------------------------------------------------

def _recompute_p(q, k, kvm, lse, scale, causal, i, j, block_q, block_k):
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale           # [bq, bk]
    s = s + (1.0 - kvm)[None, :] * MASK_VALUE
    if causal:
        qpos = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        kpos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(qpos >= kpos, s, MASK_VALUE)
    return jnp.exp(s - lse[:, None])                          # [bq, bk]


def _dq_kernel(q_ref, k_ref, v_ref, kvm_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_scr, *, scale, causal, block_q, block_k, n_k):
    i, j = pl.program_id(2), pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    live = (j * block_k <= i * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)                 # [bq, dh]
        lse = lse_ref[0, 0, :, 0]                             # [bq]
        delta = delta_ref[0, 0, :, 0]                         # [bq]
        kvm = kvm_ref[0, 0].astype(jnp.float32)
        p = _recompute_p(q, k, kvm, lse, scale, causal, i, j,
                         block_q, block_k)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bq, bk]
        ds = p * (dp - delta[:, None]) * scale
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == n_k - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, kvm_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal, block_q,
                block_k, n_q):
    # grid = (B, H, nk, nq): program_id(2) is the k-block, (3) the q-block.
    j, i = pl.program_id(2), pl.program_id(3)

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    live = (j * block_k <= i * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, 0]
        delta = delta_ref[0, 0, :, 0]
        kvm = kvm_ref[0, 0].astype(jnp.float32)
        p = _recompute_p(q, k, kvm, lse, scale, causal, i, j,
                         block_q, block_k)                    # [bq, bk]
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bk, dh]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bq, bk]
        ds = p * (dp - delta[:, None]) * scale
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == n_q - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------

def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _compiler_params(n_seq_dims: int = 1):
    """Grid dims (B, H, outer-block) are embarrassingly parallel; only the
    innermost (accumulating) dim is order-dependent."""
    if not _HAS_PLTPU:  # pragma: no cover
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"))


def _fwd_call(q, k, v, kvm, scale, causal, block_q, block_k, interpret):
    b, h, tq, dh = q.shape
    tk = k.shape[2]
    n_q, n_k = tq // block_q, tk // block_k
    grid = (b, h, n_q, n_k)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k, n_k=n_k)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, dh), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_k, dh), lambda b_, h_, i, j: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, block_k, dh), lambda b_, h_, i, j: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, block_k), lambda b_, h_, i, j: (b_, 0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, dh), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, tq, dh), q.dtype),
            jax.ShapeDtypeStruct((b, h, tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            _vmem((block_q, _LANES), jnp.float32),
            _vmem((block_q, _LANES), jnp.float32),
            _vmem((block_q, dh), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=None if interpret else _compiler_params(),
    )(q, k, v, kvm)


def _bwd_call(q, k, v, kvm, do, lse, delta, scale, causal, block_q, block_k,
              interpret):
    b, h, tq, dh = q.shape
    tk = k.shape[2]
    n_q, n_k = tq // block_q, tk // block_k

    dq_kernel = functools.partial(_dq_kernel, scale=scale, causal=causal,
                                  block_q=block_q, block_k=block_k, n_k=n_k)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, dh), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_k, dh), lambda b_, h_, i, j: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, block_k, dh), lambda b_, h_, i, j: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, block_k), lambda b_, h_, i, j: (b_, 0, j)),
            pl.BlockSpec((1, 1, block_q, dh), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, dh),
                               lambda b_, h_, i, j: (b_, h_, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, tq, dh), q.dtype),
        scratch_shapes=[_vmem((block_q, dh), jnp.float32)],
        interpret=interpret,
        compiler_params=None if interpret else _compiler_params(),
    )(q, k, v, kvm, do, lse, delta)

    dkv_kernel = functools.partial(_dkv_kernel, scale=scale, causal=causal,
                                   block_q=block_q, block_k=block_k, n_q=n_q)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, h, n_k, n_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, dh), lambda b_, h_, j, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_k, dh), lambda b_, h_, j, i: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, block_k, dh), lambda b_, h_, j, i: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, block_k), lambda b_, h_, j, i: (b_, 0, j)),
            pl.BlockSpec((1, 1, block_q, dh), lambda b_, h_, j, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, j, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, j, i: (b_, h_, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, dh), lambda b_, h_, j, i: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, block_k, dh), lambda b_, h_, j, i: (b_, h_, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, tk, dh), k.dtype),
            jax.ShapeDtypeStruct((b, h, tk, dh), v.dtype),
        ],
        scratch_shapes=[_vmem((block_k, dh), jnp.float32),
                        _vmem((block_k, dh), jnp.float32)],
        interpret=interpret,
        compiler_params=None if interpret else _compiler_params(),
    )(q, k, v, kvm, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom VJP over the padded shapes
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, kvm, scale, causal, block_q, block_k, interpret):
    out, _ = _fwd_call(q, k, v, kvm, scale, causal, block_q, block_k,
                       interpret)
    return out


def _flash_fwd(q, k, v, kvm, scale, causal, block_q, block_k, interpret):
    out, lse = _fwd_call(q, k, v, kvm, scale, causal, block_q, block_k,
                         interpret)
    return out, (q, k, v, kvm, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, do):
    q, k, v, kvm, out, lse = res
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)                   # [B,H,Tq,1]
    dq, dk, dv = _bwd_call(q, k, v, kvm, do, lse, delta, scale, causal,
                           block_q, block_k, interpret)
    return dq, dk, dv, jnp.zeros_like(kvm)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    kv_mask: Optional[jax.Array] = None,
                    causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None) -> jax.Array:
    """softmax(scale * Q K^T + mask) V, never materializing the score matrix.

    q [B,H,Tq,Dh], k/v [B,H,Tk,Dh], kv_mask [B,Tk] (1.0 = attend) or None.
    Sequence dims are padded up to block multiples internally (padded keys
    are masked out; padded query rows are sliced off).
    """
    b, h, tq, dh = q.shape
    tk = k.shape[2]
    if scale is None:
        scale = 1.0 / (dh ** 0.5)
    if interpret is None:
        interpret = _interpret_default()
    # Default blocks 512/2048, from the r5 silicon sweep at seq 2048
    # (tok/s: 128/128 5,441 · 256/512 13,625 · 512/512 15,373 ·
    # 256/1024 15,929 · **512/2048 18,039** · 1024/2048 VMEM-OOM in the
    # dq kernel at 19.09M vs the 16M scoped stack limit). Bigger k
    # blocks cut online-softmax rescale passes; both clamp to the
    # actual sequence below, so short-seq shapes are unaffected.
    # MARIAN_FLASH_BLOCK_Q/K override at trace time for sweeps; malformed
    # values fall back to the defaults with a warning (this runs at TRACE
    # time — an uncaught ValueError here would take down a whole training
    # job over a typo'd sweep variable).
    if block_q is None:
        block_q = _env_block("MARIAN_FLASH_BLOCK_Q", 512)
    if block_k is None:
        # dq-kernel VMEM scales with block_k x dh and the sweep validated
        # 2048 only at dh=64 — the DEFAULT halves for larger heads so
        # big-head configs don't hit the 1024/2048-style VMEM OOM
        # (advisor finding). Explicit values (arg or a well-formed env
        # override) are respected verbatim — a sweep's recorded block
        # size must be the block size that actually ran.
        default_k = 2048 if dh <= 64 else 1024
        block_k = _env_block("MARIAN_FLASH_BLOCK_K", default_k)

    def _pick_block(limit: int, t: int) -> int:
        # biggest block <= limit whose grid padding wastes <= 25% of t:
        # big blocks cut online-softmax rescale passes (the r5 sweep
        # win), but a 2048 block on t=2176 would pad to 4096 and run
        # the fully-masked blocks through every kernel — padded k/q
        # blocks are NOT skipped (the causal `live` test is
        # position-only)
        b = _round_up(min(limit, _round_up(t, _LANES)), _LANES)
        while b > _LANES:
            if _round_up(t, b) - t <= max(t // 4, _LANES):
                return b
            b = (b // 2 // _LANES) * _LANES
        return _LANES

    bq = _pick_block(block_q, tq)
    bk = _pick_block(block_k, tk)
    tq_p, tk_p = _round_up(tq, bq), _round_up(tk, bk)

    if kv_mask is None:
        kvm = jnp.ones((b, 1, tk), jnp.float32)
    else:
        kvm = kv_mask.astype(jnp.float32).reshape(b, 1, tk)
    if tk_p != tk:
        kvm = jnp.pad(kvm, ((0, 0), (0, 0), (0, tk_p - tk)))
        k = jnp.pad(k, ((0, 0), (0, 0), (0, tk_p - tk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, tk_p - tk), (0, 0)))
    if tq_p != tq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, tq_p - tq), (0, 0)))

    out = _flash(q, k, v, kvm, float(scale), bool(causal), bq, bk,
                 bool(interpret))
    if tq_p != tq:
        out = out[:, :, :tq, :]
    return out
