"""Head-packed attention as a Pallas TPU kernel — the short-sequence MXU fix.

The r5 GEMM truth table (docs/PERFORMANCE.md) measured the attention
score/apply einsums at 21.7%/30.6% of MXU peak at bench shapes: a dh=64
contraction fills only half the 128-deep systolic array, and a T=48-64
output fills only ~37-50% of its lanes, so XLA's per-(b,h) batched dot
burns a full 128x128 tile pass per head while using ~a fifth of it. No
XLA flag changes tile geometry (the TVM line of work, PAPERS.md, shows
graph compilers don't recover this class automatically) — the fix is to
PACK head groups into one full tile, which this kernel does with
block-diagonal operand packing:

  scores, per group of g = 128//dh heads (g=2 at dh=64):
      [Tq, g*dh] = [q_0 | q_1]          (heads concatenated on contraction)
      [g*dh, g*Tk] = diag(k_0^T, k_1^T) (block-diagonal keys)
      one dot -> [Tq, g*Tk] = [s_0 | s_1]: contraction g*dh = 128 (full
      sublanes), output g*Tk ~ 128 (full lanes)
  apply:
      [Tq, g*Tk] = [p_0 | p_1]  @  diag(v_0, v_1) [g*Tk, g*dh]
      -> [Tq, g*dh] = [o_0 | o_1]: contraction g*Tk = 128, output 128.

The zero blocks double the nominal FLOPs, but the MXU pays per tile PASS,
not per useful FLOP: two heads per pass at full geometry vs one head per
pass at ~22% is the win (analytic ~2.3x on the score dot; silicon number
pending a tunnel window — see PERFORMANCE.md r6). The custom VJP keeps
the same packed geometry in both backward orientations: dp/dq pack the
dh- and Tk-contractions exactly like the forward, dk/dv pack the Tq
contraction by stacking the group's rows (block-diag ds^T/p^T against
row-stacked q/do).

This kernel owns the T <= packed-cap regime (NMT sentence lengths);
flash_attention.py owns the long-sequence end. Same structured-mask
interface as flash: kv_mask [B, Tk] (1.0 = attend) and/or causal.
Attention dropout and returned weights fall back to the dense path via
the dispatcher (ops/attention.py).

Shapes: q [B,H,Tq,Dh], k/v [B,H,Tk,Dh] -> out [B,H,Tq,Dh]. Compute is
f32 on the MXU regardless of input dtype.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .flash_attention import (MASK_VALUE, _HAS_PLTPU, _interpret_default,
                              _round_up)

# Sequence dims pad to multiples of 64 so a g=2 pack lands on exactly
# 128 lanes/sublanes (the MXU tile edge); g>2 packs (dh 32/16) land on
# multiples of it.
_PAD = 64


def pack_group(heads: int, dh: int) -> int:
    """Heads per MXU tile: the largest divisor of `heads` with
    g*dh <= 128. g=1 means packing buys nothing (dh > 64)."""
    g = max(1, 128 // max(dh, 1))
    while g > 1 and heads % g:
        g -= 1
    return g


def _causal_rows(i0: int, bq: int, bk: int):
    qpos = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + i0
    kpos = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return qpos >= kpos


def _packed_scores(qs, ks, kvm, scale, causal, g, bq, bk, dh):
    """The packed score dot + per-head mask/softmax. qs/ks are length-g
    lists of [bq, dh]/[bk, dh] f32 blocks; returns (packed probs
    [bq, g*bk] f32, per-head prob list)."""
    qc = jnp.concatenate(qs, axis=1)                  # [bq, g*dh]
    kc = jnp.zeros((g * dh, g * bk), jnp.float32)
    for j in range(g):
        kc = jax.lax.dynamic_update_slice(kc, ks[j].T, (j * dh, j * bk))
    s2 = jax.lax.dot_general(
        qc, kc, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * scale   # [bq, g*bk]
    live = _causal_rows(0, bq, bk) if causal else None
    ps = []
    for j in range(g):
        s = s2[:, j * bk:(j + 1) * bk]                # static lane slice
        s = s + (1.0 - kvm)[None, :] * MASK_VALUE
        if causal:
            s = jnp.where(live, s, MASK_VALUE)
        m = jnp.max(s, axis=1, keepdims=True)
        p = jnp.exp(s - m)
        # l >= 1 always (the row-max key contributes exp(0) even on a
        # fully-masked row, which then yields UNIFORM probs — exactly
        # the dense path's softmax-of-all-MASK behavior, and callers
        # discard those rows), so no zero-divisor guard is needed
        l = jnp.sum(p, axis=1, keepdims=True)
        ps.append(p / l)
    return jnp.concatenate(ps, axis=1), ps


def _fwd_kernel(q_ref, k_ref, v_ref, kvm_ref, o_ref, *, scale, causal, g,
                bq, bk, dh):
    qs = [q_ref[0, j].astype(jnp.float32) for j in range(g)]
    ks = [k_ref[0, j].astype(jnp.float32) for j in range(g)]
    kvm = kvm_ref[0].astype(jnp.float32)              # [bk]
    p2, _ = _packed_scores(qs, ks, kvm, scale, causal, g, bq, bk, dh)
    vc = jnp.zeros((g * bk, g * dh), jnp.float32)
    for j in range(g):
        vc = jax.lax.dynamic_update_slice(
            vc, v_ref[0, j].astype(jnp.float32), (j * bk, j * dh))
    o2 = jax.lax.dot_general(
        p2, vc, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)           # [bq, g*dh]
    for j in range(g):
        o_ref[0, j] = o2[:, j * dh:(j + 1) * dh].astype(o_ref.dtype)


def _bwd_kernel(q_ref, k_ref, v_ref, kvm_ref, do_ref, delta_ref,
                dq_ref, dk_ref, dv_ref, *, scale, causal, g, bq, bk, dh):
    """One pass per (b, head-group): recomputes the packed probs, then
    runs all four backward dots in packed geometry. dp and dq reuse the
    forward's dh-/Tk-contraction packing; dk and dv pack the Tq
    contraction as block-diag(ds_j^T / p_j^T) @ row-stacked (q / do)."""
    qs = [q_ref[0, j].astype(jnp.float32) for j in range(g)]
    ks = [k_ref[0, j].astype(jnp.float32) for j in range(g)]
    dos = [do_ref[0, j].astype(jnp.float32) for j in range(g)]
    kvm = kvm_ref[0].astype(jnp.float32)
    _, ps = _packed_scores(qs, ks, kvm, scale, causal, g, bq, bk, dh)

    # dp: [do_0 | do_1] @ diag(v_0^T, v_1^T) — forward-score geometry
    doc = jnp.concatenate(dos, axis=1)                # [bq, g*dh]
    vt = jnp.zeros((g * dh, g * bk), jnp.float32)
    for j in range(g):
        vt = jax.lax.dynamic_update_slice(
            vt, v_ref[0, j].astype(jnp.float32).T, (j * dh, j * bk))
    dp2 = jax.lax.dot_general(
        doc, vt, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)           # [bq, g*bk]

    dss = []
    for j in range(g):
        delta = delta_ref[0, j][:, :1]                # [bq, 1]
        dp = dp2[:, j * bk:(j + 1) * bk]
        dss.append(ps[j] * (dp - delta) * scale)

    # dq: [ds_0 | ds_1] @ diag(k_0, k_1) — forward-apply geometry
    ds2 = jnp.concatenate(dss, axis=1)                # [bq, g*bk]
    kr = jnp.zeros((g * bk, g * dh), jnp.float32)
    for j in range(g):
        kr = jax.lax.dynamic_update_slice(kr, ks[j], (j * bk, j * dh))
    dq2 = jax.lax.dot_general(
        ds2, kr, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)           # [bq, g*dh]
    for j in range(g):
        dq_ref[0, j] = dq2[:, j * dh:(j + 1) * dh].astype(dq_ref.dtype)

    # dk / dv: pack the Tq contraction — diag(ds_j^T / p_j^T) [g*bk, g*bq]
    # against the group's rows stacked [g*bq, dh]
    dst = jnp.zeros((g * bk, g * bq), jnp.float32)
    pt = jnp.zeros((g * bk, g * bq), jnp.float32)
    for j in range(g):
        dst = jax.lax.dynamic_update_slice(dst, dss[j].T, (j * bk, j * bq))
        pt = jax.lax.dynamic_update_slice(pt, ps[j].T, (j * bk, j * bq))
    qr = jnp.concatenate(qs, axis=0)                  # [g*bq, dh]
    dor = jnp.concatenate(dos, axis=0)
    dk2 = jax.lax.dot_general(
        dst, qr, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)           # [g*bk, dh]
    dv2 = jax.lax.dot_general(
        pt, dor, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    for j in range(g):
        dk_ref[0, j] = dk2[j * bk:(j + 1) * bk].astype(dk_ref.dtype)
        dv_ref[0, j] = dv2[j * bk:(j + 1) * bk].astype(dv_ref.dtype)


def _compiler_params():
    if not _HAS_PLTPU:  # pragma: no cover
        return None
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel"))


def _specs(b, g, tq, tk, dh):
    """Block specs shared by fwd and bwd: one (batch, head-group) cell
    per grid point, full (padded) sequences per cell — the kernel owns
    the short-T regime, so no k-streaming is needed."""
    qspec = pl.BlockSpec((1, g, tq, dh), lambda b_, hg: (b_, hg, 0, 0))
    kspec = pl.BlockSpec((1, g, tk, dh), lambda b_, hg: (b_, hg, 0, 0))
    mspec = pl.BlockSpec((1, tk), lambda b_, hg: (b_, 0))
    return qspec, kspec, mspec


def _fwd_call(q, k, v, kvm, scale, causal, g, interpret):
    b, h, tq, dh = q.shape
    tk = k.shape[2]
    qspec, kspec, mspec = _specs(b, g, tq, tk, dh)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               g=g, bq=tq, bk=tk, dh=dh)
    return pl.pallas_call(
        kernel,
        grid=(b, h // g),
        in_specs=[qspec, kspec, kspec, mspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b, h, tq, dh), q.dtype),
        interpret=interpret,
        compiler_params=None if interpret else _compiler_params(),
    )(q, k, v, kvm)


def _bwd_call(q, k, v, kvm, do, delta, scale, causal, g, interpret):
    b, h, tq, dh = q.shape
    tk = k.shape[2]
    qspec, kspec, mspec = _specs(b, g, tq, tk, dh)
    dspec = pl.BlockSpec((1, g, tq, 1), lambda b_, hg: (b_, hg, 0, 0))
    kernel = functools.partial(_bwd_kernel, scale=scale, causal=causal,
                               g=g, bq=tq, bk=tk, dh=dh)
    return pl.pallas_call(
        kernel,
        grid=(b, h // g),
        in_specs=[qspec, kspec, kspec, mspec, qspec, dspec],
        out_specs=[qspec, kspec, kspec],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, tq, dh), q.dtype),
            jax.ShapeDtypeStruct((b, h, tk, dh), k.dtype),
            jax.ShapeDtypeStruct((b, h, tk, dh), v.dtype),
        ],
        interpret=interpret,
        compiler_params=None if interpret else _compiler_params(),
    )(q, k, v, kvm, do, delta)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _packed(q, k, v, kvm, scale, causal, g, interpret):
    return _fwd_call(q, k, v, kvm, scale, causal, g, interpret)


def _packed_fwd(q, k, v, kvm, scale, causal, g, interpret):
    out = _fwd_call(q, k, v, kvm, scale, causal, g, interpret)
    return out, (q, k, v, kvm, out)


def _packed_bwd(scale, causal, g, interpret, res, do):
    q, k, v, kvm, out = res
    # delta = rowsum(do * o) per (b,h,row) — cheap elementwise outside
    # the kernel (the bwd kernel recomputes probs, flash-style, so no
    # stats ride the residuals)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)           # [B,H,Tq,1]
    dq, dk, dv = _bwd_call(q, k, v, kvm, do, delta, scale, causal, g,
                           interpret)
    return dq, dk, dv, jnp.zeros_like(kvm)


_packed.defvjp(_packed_fwd, _packed_bwd)


def packed_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                     kv_mask: Optional[jax.Array] = None,
                     causal: bool = False,
                     scale: Optional[float] = None,
                     interpret: Optional[bool] = None) -> jax.Array:
    """softmax(scale * Q K^T + mask) V with head-group-packed MXU tiles.

    q [B,H,Tq,Dh], k/v [B,H,Tk,Dh], kv_mask [B,Tk] (1.0 = attend) or
    None. Sequence dims pad internally to multiples of 64 (padded keys
    masked out, padded query rows sliced off; the custom VJP runs on the
    padded shapes, so cotangents of padded rows are exact zeros).
    """
    b, h, tq, dh = q.shape
    tk = k.shape[2]
    g = pack_group(h, dh)
    if scale is None:
        scale = 1.0 / (dh ** 0.5)
    if interpret is None:
        interpret = _interpret_default()

    tq_p, tk_p = _round_up(tq, _PAD), _round_up(tk, _PAD)
    if kv_mask is None:
        kvm = jnp.ones((b, tk), jnp.float32)
    else:
        kvm = kv_mask.astype(jnp.float32).reshape(b, tk)
    if tk_p != tk:
        kvm = jnp.pad(kvm, ((0, 0), (0, tk_p - tk)))
        k = jnp.pad(k, ((0, 0), (0, 0), (0, tk_p - tk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, tk_p - tk), (0, 0)))
    if tq_p != tq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, tq_p - tq), (0, 0)))

    out = _packed(q, k, v, kvm, float(scale), bool(causal), g,
                  bool(interpret))
    if tq_p != tq:
        out = out[:, :, :tq, :]
    return out
