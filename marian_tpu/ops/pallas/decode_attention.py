"""Fused beam-gather + cache-update + attention read for incremental decode.

The r5 standard-decoder step decomposition (docs/PERFORMANCE.md,
DECODE_ROOFLINE.md r5) put the beam-6 step at ~11.3 ms against a ~1 ms
roofline, dominated by software: the per-layer beam reorder (a flat row
gather of every K/V cache leaf, ~3.1 ms), the single-position
dynamic_update_slice cache writes (~1.1 ms), the attention read over the
cache (~2.1 ms), and a ~690-small-op while body at ~4 us dispatch each.
Three of those four are the SAME cache traffic done three times: gather
(read+write), DUS (read+write), attention (read).

This kernel collapses the sequence into one pass per (row, head): the
beam backpointer gather is folded into the cache READ side (the block
index map reads source row `src_rows[r]` via scalar prefetch), the new
step's K/V is inserted at `pos` in-register, the reordered+updated cache
is written back out ONCE, and the masked attention over positions <= pos
runs on the in-register block. Per layer the while body loses the
separate gather ops (2 leaves), the 2 DUS writes, and the separate
score/softmax/apply chain — the op-COUNT lever the r5 falsification
identified as the real small-batch bottleneck (bench_decode.py reports
the compiled while-body op count to track it).

The beam loop contract moves with it (translator/beam_search.py): the
self-attention caches are no longer reordered after top-k; the chosen
backpointers ride the carry as flat source rows and are applied by the
NEXT step's kernel. Caches lag the beam by one step by construction and
every read goes through the pending map, so the fixpoint is identical.
src_rows=None runs the identity gather — but with nothing to fold, the
full-cache write-back is pure extra HBM traffic vs the unfused
single-position DUS, so 'auto' fuses only when a beam reorder exists
(beam_src passed); greedy/scoring decode takes the kernel only under an
explicit --transformer-fused-decode-attention on (A/Bs, tests).

Shapes: q/k_new/v_new [R,H,1,Dh], cache_k/v [R,H,L,Dh], src_rows [R]
int32, pos scalar int32 -> (out [R,H,1,Dh], new_k, new_v [R,H,L,Dh]).
Inference-only (no VJP). Compute is f32; caches keep their dtype.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .flash_attention import MASK_VALUE, _HAS_PLTPU, _interpret_default

if _HAS_PLTPU:
    from jax.experimental.pallas import tpu as pltpu
else:  # pragma: no cover — CPU-only envs without TPU lowering registration
    pltpu = None


def _kernel(src_ref, pos_ref, q_ref, kn_ref, vn_ref, ck_ref, cv_ref,
            o_ref, nk_ref, nv_ref, *, scale, max_len):
    # pos is per-row ([R] scalar-prefetch vector — scalar callers are
    # broadcast before the call): rows of different ages can share one
    # step, the contract the paged iteration path (kv_pool.py) relies on
    pos = pos_ref[pl.program_id(0)]
    # the gathered source row arrived via the block index map; fold the
    # new position in and materialize the reordered cache in one write
    kc = jax.lax.dynamic_update_slice(
        ck_ref[0, 0], kn_ref[0, 0].astype(ck_ref.dtype), (pos, 0))
    vc = jax.lax.dynamic_update_slice(
        cv_ref[0, 0], vn_ref[0, 0].astype(cv_ref.dtype), (pos, 0))
    nk_ref[0, 0] = kc
    nv_ref[0, 0] = vc
    qv = q_ref[0, 0].astype(jnp.float32)              # [1, dh]
    s = jax.lax.dot_general(
        qv, kc.astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale   # [1, L]
    steps = jax.lax.broadcasted_iota(jnp.int32, (1, max_len), 1)
    s = jnp.where(steps <= pos, s, MASK_VALUE)
    m = jnp.max(s, axis=1, keepdims=True)
    p = jnp.exp(s - m)
    p = p / jnp.sum(p, axis=1, keepdims=True)         # pos 0 always live
    o = jax.lax.dot_general(
        p, vc.astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)           # [1, dh]
    o_ref[0, 0] = o.astype(o_ref.dtype)


def _reference(q, k_new, v_new, cache_k, cache_v, pos, src_rows, scale):
    """Pure-jnp fallback (oversized caches past the VMEM cap, or a
    backend without pltpu): the exact unfused sequence the kernel
    replaces — flat row gather, DUS at pos, masked softmax read.
    ``pos`` may be a scalar or a per-row [R] vector."""
    if src_rows is not None:
        cache_k = cache_k[src_rows]
        cache_v = cache_v[src_rows]
    pos_arr = jnp.asarray(pos, jnp.int32)
    if pos_arr.ndim == 0:
        cache_k = jax.lax.dynamic_update_slice(
            cache_k, k_new.astype(cache_k.dtype), (0, 0, pos, 0))
        cache_v = jax.lax.dynamic_update_slice(
            cache_v, v_new.astype(cache_v.dtype), (0, 0, pos, 0))
        pos_b = pos
    else:
        def dus(c, n, p):
            return jax.lax.dynamic_update_slice(c, n.astype(c.dtype),
                                                (0, p, 0))
        cache_k = jax.vmap(dus)(cache_k, k_new, pos_arr)
        cache_v = jax.vmap(dus)(cache_v, v_new, pos_arr)
        pos_b = pos_arr[:, None, None, None]
    s = jnp.einsum("rhqd,rhkd->rhqk", q.astype(jnp.float32),
                   cache_k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    steps = jnp.arange(cache_k.shape[2])[None, None, None, :]
    s = jnp.where(steps <= pos_b, s, MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("rhqk,rhkd->rhqd", p, cache_v.astype(jnp.float32),
                     preferred_element_type=jnp.float32).astype(q.dtype)
    return out, cache_k, cache_v


def decode_attention(q: jax.Array, k_new: jax.Array, v_new: jax.Array,
                     cache_k: jax.Array, cache_v: jax.Array,
                     pos, src_rows: Optional[jax.Array] = None,
                     scale: Optional[float] = None,
                     interpret: Optional[bool] = None
                     ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One fused decode-attention step; see module docstring.

    `pos` may be a traced scalar (the decode loop's time index) or a
    per-row [R] vector (iteration-level decoding: rows of different ages
    share one step — the dense comparator for the paged pool path);
    `src_rows` is the pending beam backpointer map as FLAT source rows
    (None = identity, the greedy/scoring case). Returns
    (context [R,H,1,Dh], new_cache_k, new_cache_v).
    """
    r, h, _, dh = q.shape
    max_len = cache_k.shape[2]
    if scale is None:
        scale = 1.0 / (dh ** 0.5)
    if interpret is None:
        interpret = _interpret_default()

    from ..auto_tuner import decode_attention_max_len
    if not _HAS_PLTPU or max_len > decode_attention_max_len(dh):
        # degrade, don't OOM: a [L, dh] block per grid cell must fit the
        # VMEM budget (auto_tuner scales the cap down for wide heads)
        return _reference(q, k_new, v_new, cache_k, cache_v, pos,
                          src_rows, float(scale))

    if src_rows is None:
        src_rows = jnp.arange(r, dtype=jnp.int32)
    # per-row positions in the scalar-prefetch slot; scalar callers
    # broadcast (bitwise-identical: the kernel reads pos_ref[row])
    pos_arr = jnp.broadcast_to(
        jnp.asarray(pos, jnp.int32).reshape(-1), (r,))

    import functools
    kernel = functools.partial(_kernel, scale=float(scale),
                               max_len=max_len)
    new_spec = pl.BlockSpec((1, 1, max_len, dh),
                            lambda r_, h_, s, p: (r_, h_, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(r, h),
        in_specs=[
            pl.BlockSpec((1, 1, 1, dh), lambda r_, h_, s, p: (r_, h_, 0, 0)),
            pl.BlockSpec((1, 1, 1, dh), lambda r_, h_, s, p: (r_, h_, 0, 0)),
            pl.BlockSpec((1, 1, 1, dh), lambda r_, h_, s, p: (r_, h_, 0, 0)),
            # the fused gather: cache blocks come from the SOURCE row
            pl.BlockSpec((1, 1, max_len, dh),
                         lambda r_, h_, s, p: (s[r_], h_, 0, 0)),
            pl.BlockSpec((1, 1, max_len, dh),
                         lambda r_, h_, s, p: (s[r_], h_, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, 1, dh), lambda r_, h_, s, p: (r_, h_, 0, 0)),
            new_spec,
            new_spec,
        ],
    )
    out, new_k, new_v = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((r, h, 1, dh), q.dtype),
            jax.ShapeDtypeStruct(cache_k.shape, cache_k.dtype),
            jax.ShapeDtypeStruct(cache_v.shape, cache_v.dtype),
        ],
        interpret=bool(interpret),
    )(src_rows.astype(jnp.int32), pos_arr, q, k_new, v_new,
      cache_k, cache_v)
    return out, new_k, new_v
