"""SentencePiece vocabulary (reference: src/data/sentencepiece_vocab.cpp ::
SentencePieceVocab, which wraps the vendored SentencePiece C++ library).

Here we wrap the ``sentencepiece`` Python package on the host side; the module
is gated so environments without it still run word-level configs. Supports
on-the-fly training (``--sentencepiece-options``, ``--sentencepiece-max-lines``)
and subword-regularization sampling (``--sentencepiece-alphas``)."""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

from .vocab import VocabBase, EOS_ID, UNK_ID
from ..common import logging as log

try:
    import sentencepiece as _spm
    HAVE_SPM = True
except ImportError:  # pragma: no cover - environment-dependent
    _spm = None
    HAVE_SPM = False


class SentencePieceVocab(VocabBase):
    def __init__(self, path: str, options=None, stream_index: int = 0,
                 train_paths: Optional[List[str]] = None):
        if not HAVE_SPM:
            raise RuntimeError(
                "SentencePiece vocab requested but the 'sentencepiece' package "
                "is not installed; use a .yml word vocab or install sentencepiece")
        self.alpha = 0.0
        # --no-spm-encode: input text is ALREADY SentencePiece-encoded —
        # split on whitespace and look pieces up instead of re-encoding
        self.no_encode = bool(options.get("no-spm-encode", False)) \
            if options is not None else False
        if options is not None:
            alphas = options.get("sentencepiece-alphas", [])
            if stream_index < len(alphas):
                self.alpha = float(alphas[stream_index])
        if not os.path.exists(path):
            if not train_paths:
                raise FileNotFoundError(path)
            self._train(path, train_paths, options)
        self._sp = _spm.SentencePieceProcessor(model_file=path)

    def _train(self, path: str, train_paths: List[str], options) -> None:
        extra = (options.get("sentencepiece-options", "") if options else "")
        max_lines = (options.get("sentencepiece-max-lines", 2000000)
                     if options else 2000000)
        dim_vocabs = options.get("dim-vocabs", [32000]) if options else [32000]
        vocab_size = max(dim_vocabs) or 32000
        log.info("Training SentencePiece model {} from {}", path, ",".join(train_paths))
        _spm.SentencePieceTrainer.train(
            input=",".join(train_paths),
            model_prefix=path[:-len(".spm")] if path.endswith(".spm") else path,
            vocab_size=vocab_size,
            input_sentence_size=max_lines,
            shuffle_input_sentence=True,
            eos_id=EOS_ID, unk_id=UNK_ID, bos_id=-1, pad_id=-1,
            eos_piece="</s>", unk_piece="<unk>",
            **_parse_extra(extra),
        )
        prefix = path[:-len(".spm")] if path.endswith(".spm") else path
        os.replace(prefix + ".model", path)

    def encode(self, line: str, add_eos: bool = True, inference: bool = False) -> List[int]:
        if self.no_encode:
            ids = [self._sp.piece_to_id(t) for t in line.split()]
        elif self.alpha > 0 and not inference:
            ids = self._sp.encode(line, out_type=int, enable_sampling=True,
                                  alpha=self.alpha, nbest_size=-1)
        else:
            ids = self._sp.encode(line, out_type=int)
        if add_eos:
            ids.append(EOS_ID)
        return ids

    def decode(self, ids: Sequence[int], ignore_eos: bool = True) -> str:
        return self._sp.decode([int(i) for i in ids if not (ignore_eos and i == EOS_ID)])

    def surface(self, ids: Sequence[int]) -> List[str]:
        return [self._sp.id_to_piece(int(i)) for i in ids]

    def __len__(self) -> int:
        return self._sp.get_piece_size()


def _parse_extra(extra: str) -> dict:
    """Parse '--key=value --flag' style --sentencepiece-options string."""
    out = {}
    for tok in extra.split():
        tok = tok.lstrip("-")
        if "=" in tok:
            k, v = tok.split("=", 1)
            try:
                v = int(v)
            except ValueError:
                try:
                    v = float(v)
                except ValueError:
                    pass
            out[k] = v
        elif tok:
            out[tok] = True
    return out
