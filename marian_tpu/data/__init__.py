from .vocab import VocabBase, DefaultVocab, create_vocab, EOS_ID, UNK_ID
from .corpus import Corpus, CorpusState, SentenceTuple, TextInput
from .batch_generator import (BatchGenerator, CorpusBatch, SubBatch, make_batch,
                              bucket_length, bucket_batch_size,
                              DEFAULT_LENGTH_BUCKETS)
from .shortlist import (Shortlist, ShortlistGenerator, LexicalShortlistGenerator,
                        parse_shortlist_options)
from .alignment import WordAlignment, hard_alignment_from_soft

__all__ = [
    "VocabBase", "DefaultVocab", "create_vocab", "EOS_ID", "UNK_ID",
    "Corpus", "CorpusState", "SentenceTuple", "TextInput",
    "BatchGenerator", "CorpusBatch", "SubBatch", "make_batch",
    "bucket_length", "bucket_batch_size", "DEFAULT_LENGTH_BUCKETS",
    "Shortlist", "ShortlistGenerator", "LexicalShortlistGenerator",
    "parse_shortlist_options",
    "WordAlignment", "hard_alignment_from_soft",
]
