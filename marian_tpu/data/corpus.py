"""Line-parallel corpora with epoch shuffling and exact-resume positions.

Rebuild of reference src/data/corpus.cpp :: Corpus/CorpusBase and
src/data/corpus_sqlite.cpp (resumability). A SentenceTuple is one training
example across streams (source ∥ target ∥ optional alignment ∥ weights).

Resume design: instead of the reference's SQLite corpus (O(1) mid-epoch
restart) we checkpoint the iterator state — (epoch, position-in-epoch,
shuffle seed) — and fast-forward deterministically on restore; the shuffle
permutation is a function of (seed, epoch) so a restart reproduces the same
order without temp files.
"""

from __future__ import annotations

import dataclasses
import gzip
import os
from typing import Iterator, List, Optional, Sequence

import numpy as np

from .vocab import VocabBase
from ..common import logging as log


@dataclasses.dataclass
class SentenceTuple:
    """One example: token-id sequences per stream (reference:
    src/data/corpus_base.h :: SentenceTuple)."""
    idx: int                      # corpus line number (for alignments/weights)
    streams: List[List[int]]      # token ids per stream, EOS-terminated
    alignment: Optional[list] = None
    weights: Optional[List[float]] = None

    @property
    def src(self) -> List[int]:
        return self.streams[0]

    @property
    def trg(self) -> List[int]:
        return self.streams[-1]


def _open_maybe_gz(path: str):
    if path.endswith(".gz"):
        return gzip.open(path, "rt", encoding="utf-8")
    return open(path, "r", encoding="utf-8")


@dataclasses.dataclass
class CorpusState:
    """Serialized into training progress for exact resume."""
    epoch: int = 0
    position: int = 0   # sentences already yielded in this epoch
    seed: int = 1

    def as_dict(self):
        # Positions are backend-specific: python counts raw corpus lines,
        # native indexes its length-filtered order. The tag lets resume
        # detect a --data-backend switch instead of silently seeking to the
        # wrong sentence (ADVICE r1).
        return {**dataclasses.asdict(self), "backend": "python"}

    @classmethod
    def from_dict(cls, d):
        if not d:
            return cls()
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


class Corpus:
    """Reads N parallel text files, encodes with vocabs, yields SentenceTuples.

    shuffle: 'data' (shuffle sentences each epoch), 'batches'/'none' handled
    by the BatchGenerator. Length filtering follows --max-length /
    --max-length-crop semantics.
    """

    def __init__(self, paths: Sequence[str], vocabs: Sequence[VocabBase],
                 options=None, inference: bool = False,
                 state: Optional[CorpusState] = None):
        # --tsv: ONE tab-separated file carries every stream (reference:
        # CorpusBase TSV mode); --tsv-fields pins the column count,
        # defaulting to the vocab count
        self.tsv = bool(options.get("tsv", False)) if options else False
        self.tsv_fields = (int(options.get("tsv-fields", 0) or 0)
                           if options else 0)
        # --input-reorder: permutation mapping stream i ← column perm[i]
        self.input_reorder = [int(i) for i in
                              (options.get("input-reorder", []) or [])] \
            if options else []
        if self.tsv:
            if len(paths) != 1:
                raise ValueError(
                    f"--tsv expects ONE tab-separated train file, got "
                    f"{len(paths)}")
            n_fields = self.tsv_fields or len(vocabs)
            if n_fields != len(vocabs):
                raise ValueError(
                    f"--tsv-fields {n_fields} must match the number of "
                    f"--vocabs ({len(vocabs)})")
            paths = list(paths) * len(vocabs)   # stream i = column i
        assert len(paths) == len(vocabs), (paths, len(vocabs))
        self.paths = list(paths)
        self.vocabs = list(vocabs)
        self.inference = inference
        self.max_length = int(options.get("max-length", 50)) if options else 10**9
        self.max_length_crop = bool(options.get("max-length-crop", False)) if options else False
        self.shuffle_mode = (options.get("shuffle", "data") if options else "none")
        self.right_left = bool(options.get("right-left", False)) if options else False
        # target-stream id reversal applies to teacher-forced streams
        # (training, scoring); decode-time TextInput leaves targets alone
        # (the printer un-reverses hypotheses instead). The n-best
        # rescorer overrides this to score hypotheses against an R2L
        # model (reverse_target=True despite inference encoding).
        self.reverse_target = self.right_left and not inference
        self.all_caps_every = int(options.get("all-caps-every", 0)) if options else 0
        self.title_case_every = int(options.get("english-title-case-every", 0)) if options else 0
        self.state = state or CorpusState(
            seed=int(options.get("seed", 1)) or 1 if options else 1)
        self._lines_cache: Optional[List[List[str]]] = None
        # guided alignment / data weighting side-streams
        self.align_path = None
        self.weight_path = None
        if options is not None:
            ga = options.get("guided-alignment", "none")
            if ga and ga != "none" and os.path.exists(str(ga)):
                self.align_path = str(ga)
            dw = options.get("data-weighting", None)
            if dw:
                self.weight_path = str(dw)

    # -- raw line access ----------------------------------------------------
    def _read_all(self) -> List[List[str]]:
        """Read the full corpus into RAM (the reference offers in-RAM shuffle
        via --shuffle-in-ram; NMT corpora of the baseline configs fit)."""
        if self._lines_cache is None:
            if self.tsv:
                with _open_maybe_gz(self.paths[0]) as fh:
                    rows = [l.rstrip("\n").split("\t") for l in fh]
                k = len(self.vocabs)
                for i, row in enumerate(rows):
                    if len(row) != k:
                        raise ValueError(
                            f"--tsv: line {i + 1} of {self.paths[0]} has "
                            f"{len(row)} fields, expected {k}")
                cols = list(range(k))
                if self.input_reorder:   # --input-reorder permutation
                    if sorted(self.input_reorder) != cols:
                        raise ValueError(
                            f"--input-reorder {self.input_reorder} is not "
                            f"a permutation of 0..{k - 1}")
                    cols = self.input_reorder
                streams = [[row[j] for row in rows] for j in cols]
            else:
                streams = []
                for p in self.paths:
                    with _open_maybe_gz(p) as fh:
                        streams.append([l.rstrip("\n") for l in fh])
            n = len(streams[0])
            for p, s in zip(self.paths[1:], streams[1:]):
                if len(s) != n:
                    raise ValueError(
                        f"Corpus streams differ in length: {self.paths[0]} has {n}, "
                        f"{p} has {len(s)}")
            if self.align_path:
                with _open_maybe_gz(self.align_path) as fh:
                    aligns = [l.rstrip("\n") for l in fh]
                if len(aligns) != n:
                    raise ValueError("Alignment file length mismatch")
                self._aligns = aligns
            else:
                self._aligns = None
            if self.weight_path:
                with _open_maybe_gz(self.weight_path) as fh:
                    weights = [l.rstrip("\n") for l in fh]
                if len(weights) != n:
                    raise ValueError("Weight file length mismatch")
                self._weights = weights
            else:
                self._weights = None
            self._lines_cache = streams
        return self._lines_cache

    def __len__(self) -> int:
        return len(self._read_all()[0])

    # -- epoch iteration ----------------------------------------------------
    def _permutation(self, epoch: int) -> np.ndarray:
        n = len(self)
        if self.shuffle_mode != "data" or self.inference:
            return np.arange(n)
        rs = np.random.RandomState((self.state.seed + 0x9E37 * (epoch + 1)) % (2**31))
        return rs.permutation(n)

    def _augment(self, line: str, sent_no: int) -> str:
        # --all-caps-every / --english-title-case-every (corpus.cpp augmentation)
        if self.all_caps_every and sent_no % self.all_caps_every == self.all_caps_every - 1:
            return line.upper()
        if self.title_case_every and sent_no % self.title_case_every == self.title_case_every - 1:
            return " ".join(w[:1].upper() + w[1:] if w else w for w in line.split(" "))
        return line

    def _make_tuple(self, idx: int, sent_no: int) -> Optional[SentenceTuple]:
        streams_txt = self._read_all()
        encoded: List[List[int]] = []
        for si, (lines, vocab) in enumerate(zip(streams_txt, self.vocabs)):
            text = self._augment(lines[idx], sent_no)
            ids = vocab.encode(text, add_eos=True, inference=self.inference)
            # length filter: count incl. EOS like Marian (maxLengthCrop keeps EOS)
            if len(ids) > self.max_length + 1:
                if self.max_length_crop or self.inference:
                    ids = ids[: self.max_length] + [vocab.eos_id]
                else:
                    return None
            # --right-left: train the target right-to-left (reference:
            # corpus rightLeft_ reversing the target stream, EOS stays last)
            if self.reverse_target and si == len(self.vocabs) - 1:
                ids = ids[-2::-1] + [ids[-1]]
            encoded.append(ids)
        align = None
        if getattr(self, "_aligns", None) is not None:
            from .alignment import WordAlignment
            align = WordAlignment.parse(self._aligns[idx])
        weights = None
        if getattr(self, "_weights", None) is not None:
            weights = [float(x) for x in self._weights[idx].split()]
        return SentenceTuple(idx, encoded, alignment=align, weights=weights)

    def __iter__(self) -> Iterator[SentenceTuple]:
        """Yield the remainder of the current epoch from self.state.position,
        then advance epochs indefinitely (the Train driver bounds epochs)."""
        while True:
            perm = self._permutation(self.state.epoch)
            n = len(perm)
            while self.state.position < n:
                pos = self.state.position
                self.state.position += 1
                st = self._make_tuple(int(perm[pos]), pos)
                if st is not None:
                    yield st
            self.state.epoch += 1
            self.state.position = 0
            return  # one epoch per iterator pass; Train driver loops epochs

    def iter_epoch(self) -> Iterator[SentenceTuple]:
        return iter(self)

    def restore(self, state_dict) -> None:
        self.state = CorpusState.from_dict(state_dict)


class TextInput(Corpus):
    """stdin/string input for the decoder/server (reference:
    src/data/text_input.cpp). No shuffling, no length filter by default."""

    def __init__(self, lines_per_stream: Sequence[Sequence[str]],
                 vocabs: Sequence[VocabBase], options=None,
                 reverse_target: bool = False):
        super().__init__(paths=["<text>"] * len(lines_per_stream), vocabs=vocabs,
                         options=None, inference=True)
        if options is not None:
            self.max_length = int(options.get("max-length", 1000))
            self.max_length_crop = True
        self.reverse_target = reverse_target
        self.shuffle_mode = "none"
        self._lines_cache = [list(s) for s in lines_per_stream]
        self._aligns = None
        self._weights = None
