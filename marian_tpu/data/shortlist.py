"""Lexical shortlists: restrict the output vocabulary per batch.

Rebuild of reference src/data/shortlist.h/.cpp :: LexicalShortlistGenerator /
Shortlist::indices. Semantics kept: given a probability table lex.s2t
(P(trg|src) from fast_align-style extraction; text lines ``src trg prob``),
the shortlist for a batch is the union of

- the ``first`` most frequent target words (always includes EOS/UNK), and
- the ``best`` highest-probability translations of every source word present,
optionally pruned by probability threshold.

TPU redesign: the per-batch shortlist is padded (with EOS) to a **fixed K**
rounded up to a multiple of 128 (lane width) so the sliced output projection
``[dim, K]`` has a static shape under jit; decoding then works in shortlist
coordinates and maps back via the returned index array. (The reference slices
output embedding rows dynamically per batch; XLA gets a gather with a static
result shape instead.)
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional, Sequence

import numpy as np

from .vocab import VocabBase, EOS_ID, UNK_ID
from ..common import logging as log


class Shortlist:
    """Per-batch target-vocab subset (reference: Shortlist)."""

    def __init__(self, indices: np.ndarray):
        # sorted unique target ids, padded to fixed K with EOS_ID at front
        self.indices = indices.astype(np.int32)   # [K]

    def __len__(self) -> int:
        return len(self.indices)

    def reverse_map(self, shortlist_ids: np.ndarray) -> np.ndarray:
        """Map shortlist-coordinate ids back to full-vocab ids."""
        return self.indices[shortlist_ids]


class ShortlistGenerator:
    def generate(self, src_ids: Sequence[int]) -> Shortlist:
        raise NotImplementedError


class LexicalShortlistGenerator(ShortlistGenerator):
    def __init__(self, path: str, src_vocab: VocabBase, trg_vocab: VocabBase,
                 first: int = 100, best: int = 100, prune: float = 0.0,
                 k_multiple: int = 128, max_k: int = 0):
        self.first = first
        self.best = best
        self.k_multiple = k_multiple
        self.max_k = max_k
        # table: src_id -> [(prob, trg_id)] top-`best`, sorted desc
        table: Dict[int, List] = collections.defaultdict(list)
        if path.endswith(".npz"):
            self._load_binary(path, table, prune)
        else:
            self._load_text(path, src_vocab, trg_vocab, table, prune)
        self.table: Dict[int, np.ndarray] = {}
        self.probs: Dict[int, np.ndarray] = {}   # real P(trg|src), kept so a
        for s, lst in table.items():             # text→binary→text round trip
            lst.sort(reverse=True)               # preserves pruning behavior
            top = lst[: self.best]
            self.table[s] = np.array([t for _, t in top], dtype=np.int32)
            self.probs[s] = np.array([p for p, _ in top], dtype=np.float32)
        log.info("Loaded lexical shortlist with {} source entries", len(self.table))

    def _load_text(self, path, src_vocab, trg_vocab, table, prune):
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                parts = line.split()
                if len(parts) < 3:
                    continue
                s_w, t_w, p = parts[0], parts[1], float(parts[2])
                if p < prune:
                    continue
                s, t = src_vocab[s_w], trg_vocab[t_w]
                if s != UNK_ID or s_w == "<unk>":
                    table[s].append((p, t))

    def _load_binary(self, path, table, prune):
        """Binary shortlist (QuickSand-style packed table; our marian-conv
        writes this npz layout: srcs/trgs/probs arrays)."""
        npz = np.load(path)
        for s, t, p in zip(npz["srcs"], npz["trgs"], npz["probs"]):
            if p >= prune:
                table[int(s)].append((float(p), int(t)))

    def save_binary(self, path: str) -> None:
        srcs, trgs, probs = [], [], []
        for s, arr in self.table.items():
            ps = self.probs[s]
            for rank, t in enumerate(arr):
                srcs.append(s)
                trgs.append(int(t))
                probs.append(float(ps[rank]))
        np.savez(path if path.endswith(".npz") else path + ".npz",
                 srcs=np.array(srcs, np.int32), trgs=np.array(trgs, np.int32),
                 probs=np.array(probs, np.float32))

    def generate(self, src_ids: Sequence[int]) -> Shortlist:
        chosen = set(range(min(self.first, 10**9)))  # top-`first` frequent ids
        chosen.add(EOS_ID)
        chosen.add(UNK_ID)
        for s in set(int(x) for x in src_ids):
            arr = self.table.get(s)
            if arr is not None:
                chosen.update(int(t) for t in arr)
        idx = np.array(sorted(chosen), dtype=np.int32)
        # pad to static K (multiple of k_multiple lanes) with EOS
        k = max(self.k_multiple,
                ((len(idx) + self.k_multiple - 1) // self.k_multiple) * self.k_multiple)
        if self.max_k:
            k = min(k, self.max_k)
            idx = idx[:k]
        out = np.full((k,), EOS_ID, dtype=np.int32)
        out[: len(idx)] = idx
        return Shortlist(out)


def parse_shortlist_options(vals: Sequence, src_vocab, trg_vocab) -> Optional[LexicalShortlistGenerator]:
    """--shortlist path [first] [best] [prune] (reference: translator.h)."""
    if not vals:
        return None
    path = str(vals[0])
    first = int(vals[1]) if len(vals) > 1 else 100
    best = int(vals[2]) if len(vals) > 2 else 100
    prune = float(vals[3]) if len(vals) > 3 else 0.0
    return LexicalShortlistGenerator(path, src_vocab, trg_vocab, first, best, prune)
