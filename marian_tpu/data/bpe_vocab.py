"""In-repo BPE subword vocabulary — the fallback that keeps
``--sentencepiece``-style workflows (train on raw text, no pre-built
vocab) working when the ``sentencepiece`` wheel is absent from the image
(reference: src/data/sentencepiece_vocab.cpp wraps a VENDORED
SentencePiece precisely so the capability never depends on the
environment; vendoring the C++ library is out of scope here, so the
capability is preserved with a pure-Python byte-pair-encoding model
behind the same VocabBase interface).

Not byte-compatible with real ``.spm`` protobuf models (loading one
without the wheel raises with a clear message); the model file is JSON
with a versioned magic line. Word-initial pieces carry the SPM-style
"▁" marker so decode is a join + marker replacement.

Subword regularization (``--sentencepiece-alphas``) maps to BPE-dropout
(Provilkov et al. 2020): during training-time encoding each merge is
skipped with probability alpha, yielding sampled segmentations with the
same regularizing effect as SPM's unigram sampling.
"""

from __future__ import annotations

import heapq
import json
import os
import random
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .vocab import VocabBase, EOS_ID, UNK_ID
from ..common import logging as log

_MAGIC = "marian_tpu-bpe-v1"
_WB = "▁"          # ▁ word-initial marker (SPM convention)


def train_bpe(lines: Iterable[str], vocab_size: int,
              max_lines: int = 2000000) -> Tuple[List[str],
                                                 List[Tuple[str, str]]]:
    """Learn a BPE model: returns (pieces, merges).

    Classic subword-nmt algorithm with the pair→words index so each
    merge only re-counts the words it touched (not the whole corpus):
    ids 0/1 are reserved for </s>/<unk>; then single characters by
    frequency; then merge outputs in merge order.
    """
    word_freq: Counter = Counter()
    for i, line in enumerate(lines):
        if i >= max_lines:
            break
        for w in line.split():
            word_freq[_WB + w] += 1

    # word → current symbol tuple
    words: Dict[str, Tuple[str, ...]] = {w: tuple(w) for w in word_freq}
    char_freq: Counter = Counter()
    for w, f in word_freq.items():
        for ch in w:
            char_freq[ch] += f

    pieces: List[str] = ["</s>", "<unk>"]
    pieces += [c for c, _ in
               sorted(char_freq.items(), key=lambda kv: (-kv[1], kv[0]))]

    def _pairs(sym: Tuple[str, ...]) -> Iterable[Tuple[str, str]]:
        return zip(sym, sym[1:])

    pair_counts: Counter = Counter()
    pair_words: Dict[Tuple[str, str], set] = {}
    for w, sym in words.items():
        f = word_freq[w]
        for pr in _pairs(sym):
            pair_counts[pr] += f
            pair_words.setdefault(pr, set()).add(w)

    # lazy-deletion max-heap over pair counts: after each merge, every
    # TOUCHED pair (count moved either direction) gets one fresh entry
    # at its final count; stale entries are skipped at pop time. A
    # linear max() scan per merge is O(pairs × merges) — hours at real
    # scale (32k merges over millions of distinct pairs); the heap makes
    # each merge O(touched·log P). Deterministic: ties pop the
    # lexicographically smallest pair (the defined order — models are
    # trained per-environment, no artifacts pin a different one).
    heap = [(-c, pr) for pr, c in pair_counts.items()]
    heapq.heapify(heap)

    merges: List[Tuple[str, str]] = []
    seen = set(pieces)
    while len(pieces) < vocab_size and heap:
        negc, best = heapq.heappop(heap)
        cur = pair_counts.get(best, 0)
        if cur != -negc:
            continue                  # stale entry (count changed since push)
        if cur < 2:
            break                     # singleton pairs don't generalize
        merged = best[0] + best[1]
        merges.append(best)
        if merged not in seen:
            pieces.append(merged)
            seen.add(merged)
        touched = set()
        for w in list(pair_words.get(best, ())):
            f = word_freq[w]
            old = words[w]
            for pr in _pairs(old):
                pair_counts[pr] -= f
                touched.add(pr)
                if pair_counts[pr] <= 0:
                    del pair_counts[pr]
                s = pair_words.get(pr)
                if s is not None:
                    s.discard(w)
                    if not s:
                        del pair_words[pr]
            new: List[str] = []
            j = 0
            while j < len(old):
                if (j + 1 < len(old) and old[j] == best[0]
                        and old[j + 1] == best[1]):
                    new.append(merged)
                    j += 2
                else:
                    new.append(old[j])
                    j += 1
            words[w] = tuple(new)
            for pr in _pairs(words[w]):
                pair_counts[pr] += f
                touched.add(pr)
                pair_words.setdefault(pr, set()).add(w)
        # one fresh entry per touched pair at its FINAL count — covers
        # decrements too (a pair whose count only ever falls must still
        # be reachable at its reduced count; pushing only on increments
        # would orphan it once its init-time entry goes stale)
        for pr in touched:
            c = pair_counts.get(pr, 0)
            if c >= 2:
                heapq.heappush(heap, (-c, pr))
    return pieces[:vocab_size], merges


class BPEVocab(VocabBase):
    """Subword vocab over a trained BPE model (drop-in for
    SentencePieceVocab where the wheel is absent)."""

    def __init__(self, path: str, options=None, stream_index: int = 0,
                 train_paths: Optional[List[str]] = None):
        self.alpha = 0.0
        self.no_encode = bool(options.get("no-spm-encode", False)) \
            if options is not None else False
        if options is not None:
            alphas = options.get("sentencepiece-alphas", [])
            if stream_index < len(alphas):
                self.alpha = float(alphas[stream_index])
        seed = int(options.get("seed", 0) or 0) if options is not None else 0
        self._rng = random.Random(seed + stream_index)
        if not os.path.exists(path):
            if not train_paths:
                raise FileNotFoundError(path)
            self._train(path, train_paths, options)
        self._load(path)

    # -- model IO -----------------------------------------------------------
    def _train(self, path: str, train_paths: List[str], options) -> None:
        dim_vocabs = (options.get("dim-vocabs", []) if options else []) \
            or [8000]
        vocab_size = max(dim_vocabs) or 8000
        max_lines = int(options.get("sentencepiece-max-lines", 2000000)
                        if options else 2000000)
        log.info("Training in-repo BPE model {} from {} (sentencepiece "
                 "wheel absent; BPE fallback, vocab {})",
                 path, ",".join(train_paths), vocab_size)
        if options is not None and options.get("sentencepiece-options", ""):
            log.warn("--sentencepiece-options are SPM-trainer flags and "
                     "do not apply to the BPE fallback (ignored)")

        def _lines():
            for tp in train_paths:
                with open(tp, "r", encoding="utf-8") as fh:
                    yield from (l.rstrip("\n") for l in fh)

        pieces, merges = train_bpe(_lines(), vocab_size, max_lines)
        with open(path, "w", encoding="utf-8") as fh:
            json.dump({"magic": _MAGIC, "pieces": pieces,
                       "merges": [list(m) for m in merges]}, fh,
                      ensure_ascii=False)

    def _load(self, path: str) -> None:
        with open(path, "rb") as fh:
            head = fh.read(64)
        if _MAGIC.encode() not in head:
            raise RuntimeError(
                f"{path} is not a {_MAGIC} model — it looks like a real "
                f"SentencePiece binary, which needs the 'sentencepiece' "
                f"package (absent in this environment). Re-train with "
                f"this toolkit to get the BPE-fallback format, or "
                f"install the wheel.")
        with open(path, "r", encoding="utf-8") as fh:
            m = json.load(fh)
        self._pieces: List[str] = m["pieces"]
        self._p2i = {p: i for i, p in enumerate(self._pieces)}
        self._ranks = {tuple(pr): r for r, pr in enumerate(m["merges"])}
        # native C++ encoder for the deterministic hot path (reference:
        # vendored C++ SentencePiece); id-identical to the Python merge
        # loop, falls back silently if the toolchain can't build it
        self._native = None
        try:
            from ..native import NativeBPEEncoder
            self._native = NativeBPEEncoder(
                self._pieces, [tuple(pr) for pr in m["merges"]])
        except Exception as e:  # noqa: BLE001 — optional fast path
            log.info("native BPE encoder unavailable ({}); using the "
                     "Python path", e)

    # -- encoding -----------------------------------------------------------
    def _bpe_word(self, word: str, dropout: float) -> List[str]:
        sym = list(word)
        if not sym:
            return sym
        while len(sym) > 1:
            cand = [(self._ranks[pr], j)
                    for j, pr in enumerate(zip(sym, sym[1:]))
                    if tuple(pr) in self._ranks
                    and not (dropout > 0
                             and self._rng.random() < dropout)]
            if not cand:
                break
            _, j = min(cand)
            sym[j:j + 2] = [sym[j] + sym[j + 1]]
        return sym

    def encode(self, line: str, add_eos: bool = True,
               inference: bool = False) -> List[int]:
        if self.no_encode:
            ids = [self._p2i.get(t, UNK_ID) for t in line.split()]
        else:
            drop = self.alpha if not inference else 0.0
            if drop == 0.0 and self._native is not None:
                ids = self._native.encode(line, add_eos=add_eos)
                return ids
            ids = []
            for w in line.split():
                for p in self._bpe_word(_WB + w, drop):
                    ids.append(self._p2i.get(p, UNK_ID))
        if add_eos:
            ids.append(EOS_ID)
        return ids

    def decode(self, ids: Sequence[int], ignore_eos: bool = True) -> str:
        toks = [self._pieces[int(i)] for i in ids
                if int(i) < len(self._pieces)
                and not (ignore_eos and int(i) == EOS_ID)]
        return "".join(toks).replace(_WB, " ").strip()

    def surface(self, ids: Sequence[int]) -> List[str]:
        return [self._pieces[int(i)] if int(i) < len(self._pieces)
                else "<unk>" for i in ids]

    def __len__(self) -> int:
        return len(self._pieces)
