"""Vocabularies: frequency-sorted word↔id maps with Marian's conventions.

Rebuild of reference src/data/vocab.cpp :: Vocab::create and
src/data/default_vocab.cpp :: DefaultVocab. Conventions kept:

- special tokens ``</s>`` = 0 (EOS) and ``<unk>`` = 1 (UNK);
- vocab files are YAML/JSON maps ``word: id`` (``.yml``/``.yaml``/``.json``)
  or plain text one-word-per-line (ids by line order after specials);
- ``Vocab.create`` dispatches on file extension: ``.spm`` → SentencePiece,
  ``.fsv`` → factored vocab, else default;
- creating a missing vocab from training data (marian-vocab equivalent).
"""

from __future__ import annotations

import collections
import json
import os
from typing import Dict, Iterable, List, Optional, Sequence

import yaml

from ..common import logging as log

DEFAULT_EOS_STR = "</s>"
DEFAULT_UNK_STR = "<unk>"
EOS_ID = 0
UNK_ID = 1


class VocabBase:
    """Interface (reference: src/data/vocab_base.h :: IVocab)."""

    def encode(self, line: str, add_eos: bool = True, inference: bool = False) -> List[int]:
        raise NotImplementedError

    def decode(self, ids: Sequence[int], ignore_eos: bool = True) -> str:
        raise NotImplementedError

    def surface(self, ids: Sequence[int]) -> List[str]:
        """Per-token strings (for alignments / debugging)."""
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError

    @property
    def eos_id(self) -> int:
        return EOS_ID

    @property
    def unk_id(self) -> int:
        return UNK_ID


class DefaultVocab(VocabBase):
    """Word-level vocab from YAML/JSON/text (reference: default_vocab.cpp)."""

    def __init__(self, word2id: Dict[str, int]):
        self._w2i = dict(word2id)
        self._i2w: Dict[int, str] = {}
        for w, i in self._w2i.items():
            self._i2w[i] = w
        # ensure specials
        if self._w2i.get(DEFAULT_EOS_STR, EOS_ID) != EOS_ID or \
           self._w2i.get(DEFAULT_UNK_STR, UNK_ID) != UNK_ID:
            raise ValueError(
                f"Vocab must map {DEFAULT_EOS_STR}→{EOS_ID}, {DEFAULT_UNK_STR}→{UNK_ID}")
        self._w2i.setdefault(DEFAULT_EOS_STR, EOS_ID)
        self._w2i.setdefault(DEFAULT_UNK_STR, UNK_ID)
        self._i2w.setdefault(EOS_ID, DEFAULT_EOS_STR)
        self._i2w.setdefault(UNK_ID, DEFAULT_UNK_STR)
        self._size = max(self._i2w) + 1

    # -- IO -----------------------------------------------------------------
    @classmethod
    def load(cls, path: str, max_size: int = 0) -> "DefaultVocab":
        if path.endswith((".yml", ".yaml")):
            with open(path, "r", encoding="utf-8") as fh:
                m = yaml.safe_load(fh)
        elif path.endswith(".json"):
            with open(path, "r", encoding="utf-8") as fh:
                m = json.load(fh)
        else:  # plain text, one word per line
            m = {}
            with open(path, "r", encoding="utf-8") as fh:
                next_id = 2
                for line in fh:
                    w = line.rstrip("\n")
                    if not w or w in (DEFAULT_EOS_STR, DEFAULT_UNK_STR):
                        continue
                    m[w] = next_id
                    next_id += 1
            m[DEFAULT_EOS_STR] = EOS_ID
            m[DEFAULT_UNK_STR] = UNK_ID
        if max_size:
            m = {w: i for w, i in m.items() if i < max_size}
        return cls(m)

    def save(self, path: str) -> None:
        # Marian writes ids in value order; yaml map with sorted-by-id keys.
        with open(path, "w", encoding="utf-8") as fh:
            for i, w in sorted(self._i2w.items()):
                yaml.safe_dump({w: i}, fh, default_flow_style=False,
                               allow_unicode=True)

    @classmethod
    def build(cls, lines: Iterable[str], max_size: int = 0) -> "DefaultVocab":
        """Frequency-sorted vocab from raw text (marian-vocab equivalent:
        reference src/command/marian_vocab.cpp)."""
        counter: collections.Counter = collections.Counter()
        for line in lines:
            counter.update(line.split())
        words = [w for w, _ in sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))]
        if max_size:
            words = words[: max(0, max_size - 2)]
        m = {DEFAULT_EOS_STR: EOS_ID, DEFAULT_UNK_STR: UNK_ID}
        for j, w in enumerate(words):
            m[w] = j + 2
        return cls(m)

    # -- encode/decode ------------------------------------------------------
    def encode(self, line: str, add_eos: bool = True, inference: bool = False) -> List[int]:
        ids = [self._w2i.get(w, UNK_ID) for w in line.split()]
        if add_eos:
            ids.append(EOS_ID)
        return ids

    def decode(self, ids: Sequence[int], ignore_eos: bool = True) -> str:
        return " ".join(self.surface(ids, ignore_eos))

    def surface(self, ids: Sequence[int], ignore_eos: bool = True) -> List[str]:
        out = []
        for i in ids:
            if ignore_eos and i == EOS_ID:
                continue
            out.append(self._i2w.get(int(i), DEFAULT_UNK_STR))
        return out

    def __len__(self) -> int:
        return self._size

    def __getitem__(self, word: str) -> int:
        return self._w2i.get(word, UNK_ID)

    def id_to_word(self, i: int) -> str:
        return self._i2w.get(int(i), DEFAULT_UNK_STR)

    def word_to_id_map(self) -> Dict[str, int]:
        """Full word→id mapping (consumed by the native data loader)."""
        return dict(self._w2i)


def create_vocab(path: Optional[str], options=None, stream_index: int = 0,
                 train_paths: Optional[List[str]] = None,
                 max_size: int = 0) -> VocabBase:
    """Vocab factory (reference: Vocab::create). Dispatch on extension;
    builds the vocab from training data when the file does not exist."""
    if path and path.endswith(".spm"):
        from .spm_vocab import HAVE_SPM, SentencePieceVocab
        if os.path.exists(path):
            # dispatch an EXISTING model by content, not environment: a
            # BPE-fallback file must load as BPE even after the wheel
            # appears (else SentencePieceProcessor dies with an opaque
            # protobuf error on our JSON)
            with open(path, "rb") as fh:
                head = fh.read(64)
            if b"marian_tpu-bpe-v1" in head:
                from .bpe_vocab import BPEVocab
                return BPEVocab(path, options=options,
                                stream_index=stream_index)
        if HAVE_SPM:
            return SentencePieceVocab(path, options=options,
                                      stream_index=stream_index,
                                      train_paths=train_paths)
        # wheel absent: the in-repo BPE fallback keeps raw-text →
        # subword-vocab → train workflows alive (not byte-compatible
        # with real .spm binaries — bpe_vocab.py refuses those loudly)
        from .bpe_vocab import BPEVocab
        log.warn("sentencepiece package not installed — using the "
                 "in-repo BPE fallback for {} (SPM-format models are "
                 "not loadable without the wheel)", path)
        return BPEVocab(path, options=options, stream_index=stream_index,
                        train_paths=train_paths)
    if path and path.endswith(".fsv"):
        from .factored_vocab import FactoredVocab
        return FactoredVocab.load(path)
    if path and os.path.exists(path):
        return DefaultVocab.load(path, max_size=max_size)
    if path and train_paths:
        log.info("Building vocabulary {} from {}", path, ",".join(train_paths))

        def _lines():
            for tp in train_paths:
                with open(tp, "r", encoding="utf-8") as fh:
                    yield from (l.rstrip("\n") for l in fh)

        v = DefaultVocab.build(_lines(), max_size=max_size)
        v.save(path)
        return v
    raise FileNotFoundError(f"Vocabulary file {path} not found and no data to build it")
