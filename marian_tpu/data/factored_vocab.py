"""Factored vocabulary: words are a lemma ⊕ factor tags (``Hello|ci|gl-``).

Rebuild of reference src/data/factored_vocab.cpp :: FactoredVocab (consumed
by src/layers/logits.cpp for the factored softmax and by the factored
embedding composition in src/layers/embedding.cpp). Config #4 of the
baseline matrix uses this.

File format (``.fsv``): plain text, one factored word form per line,
``lemma|factor|factor...``; ids are line order after the specials
(``</s>`` = 0, ``<unk>`` = 1 are prepended if absent); ``#`` comments and
blank lines skipped.

Factor groups: a factor name belongs to the group named by its alphabetic
stem — ``gl+``/``gl-`` → group ``gl``; ``ci``/``cn``/``ca`` → group ``c``
(capitalization: initial/none/all); ``wb``/``we`` → group ``w``; i.e. the
name minus a trailing ``+``/``-``, else its first letter. Every factored
form must carry at most one factor per group.

The *unit* axis concatenates [lemmas..., factors...] plus one PAD slot —
this is the axis the embedding table and output matrix are sized over.
``factor_indices`` maps word id → its units (PAD where a group is absent):
the TPU model computes embeddings as a masked gather-sum over units and
output scores as a sum of per-group log-softmaxes gathered back to word
space (layers/logits.py) — Marian's Logits class does the same group-wise
combination lazily on the GPU graph.

Surface realization on decode applies the capitalization factors and the
glue factors (``gl+`` = no space to the left, ``gr+`` = none to the right).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .vocab import (DEFAULT_EOS_STR, DEFAULT_UNK_STR, EOS_ID, UNK_ID,
                    VocabBase)


def _group_of(factor: str) -> str:
    if factor and factor[-1] in "+-":
        return factor[:-1]
    return factor[:1]


class FactoredVocab(VocabBase):
    factored = True

    def __init__(self, forms: List[str]):
        # forms[i] = full factored string for word id i
        self._forms = forms
        self._form2id: Dict[str, int] = {f: i for i, f in enumerate(forms)}

        lemmas: List[str] = []
        lemma_idx: Dict[str, int] = {}
        factors: List[str] = []
        factor_idx: Dict[str, int] = {}
        groups: List[str] = []
        parsed: List[Tuple[str, List[str]]] = []
        for f in forms:
            parts = f.split("|")
            lemma, facs = parts[0], parts[1:]
            if lemma not in lemma_idx:
                lemma_idx[lemma] = len(lemmas)
                lemmas.append(lemma)
            for fac in facs:
                if fac not in factor_idx:
                    factor_idx[fac] = len(factors)
                    factors.append(fac)
                    g = _group_of(fac)
                    if g not in groups:
                        groups.append(g)
            parsed.append((lemma, facs))

        self.lemmas = lemmas
        self.factors = factors
        self.groups = groups                      # factor group names
        self.n_lemmas = len(lemmas)
        self.n_units = len(lemmas) + len(factors) + 1   # + PAD
        self.pad_unit = self.n_units - 1

        # unit index of each factor (grouped contiguously for the per-group
        # softmax slices): reorder factors by group
        order = sorted(range(len(factors)),
                       key=lambda i: (groups.index(_group_of(factors[i])), i))
        self._factor_unit = {}
        slices: List[Tuple[str, int, int]] = [("lemma", 0, self.n_lemmas)]
        pos = self.n_lemmas
        for g in groups:
            start = pos
            for i in order:
                if _group_of(factors[i]) == g:
                    self._factor_unit[factors[i]] = pos
                    pos += 1
            slices.append((g, start, pos))
        self.group_slices: Tuple[Tuple[str, int, int], ...] = tuple(slices)

        # word → units table [V, 1 + n_groups]
        k = 1 + len(groups)
        tbl = np.full((len(forms), k), self.pad_unit, np.int32)
        for wid, (lemma, facs) in enumerate(parsed):
            tbl[wid, 0] = lemma_idx[lemma]
            for fac in facs:
                gi = groups.index(_group_of(fac))
                tbl[wid, 1 + gi] = self._factor_unit[fac]
        self.factor_indices = tbl

    # -- IO -----------------------------------------------------------------
    @classmethod
    def load(cls, path: str) -> "FactoredVocab":
        forms: List[str] = []
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                forms.append(line.split()[0] if " " in line else line)
        for special in (DEFAULT_UNK_STR, DEFAULT_EOS_STR):
            if special in forms:
                forms.remove(special)
            forms.insert(0, special)
        assert forms[EOS_ID] == DEFAULT_EOS_STR
        return cls(forms)

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as fh:
            for f in self._forms:
                fh.write(f + "\n")

    # -- encode / decode ----------------------------------------------------
    def _lookup(self, token: str) -> int:
        wid = self._form2id.get(token)
        if wid is not None:
            return wid
        # surface-form analysis: try capitalization factors (the reference
        # relies on the external factored segmenter; this is the minimal
        # inverse for plain-text input)
        low = token.lower()
        for cand in (token + "|cn", low + "|ci" if token[:1].isupper() else None,
                     low + "|ca" if token.isupper() else None,
                     low + "|cn", low):
            if cand and cand in self._form2id:
                return self._form2id[cand]
        return UNK_ID

    def encode(self, line: str, add_eos: bool = True,
               inference: bool = False) -> List[int]:
        ids = [self._lookup(t) for t in line.split()]
        if add_eos:
            ids.append(EOS_ID)
        return ids

    def _realize(self, form: str) -> Tuple[str, bool, bool]:
        """factored form → (surface, glue_left, glue_right)."""
        parts = form.split("|")
        word, facs = parts[0], set(parts[1:])
        if "ci" in facs:
            word = word[:1].upper() + word[1:]
        elif "ca" in facs:
            word = word.upper()
        return word, ("gl+" in facs), ("gr+" in facs)

    def decode(self, ids: Sequence[int], ignore_eos: bool = True) -> str:
        out = []
        prev_glue_right = False
        for i in ids:
            if ignore_eos and i == EOS_ID:
                continue
            word, gl, gr = self._realize(self._forms[int(i)])
            if out and (gl or prev_glue_right):
                out[-1] += word
            else:
                out.append(word)
            prev_glue_right = gr
        return " ".join(out)

    def surface(self, ids: Sequence[int], ignore_eos: bool = True) -> List[str]:
        return [self._forms[int(i)] for i in ids
                if not (ignore_eos and i == EOS_ID)]

    def __len__(self) -> int:
        return len(self._forms)

    def __getitem__(self, form: str) -> int:
        return self._form2id.get(form, UNK_ID)
