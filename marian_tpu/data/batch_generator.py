"""Token-budget batch generation with XLA-friendly static shapes.

Rebuild of reference src/data/batch_generator.h :: BatchGenerator<Corpus>::
fetchBatches and src/data/corpus_base.h :: CorpusBatch/SubBatch, redesigned
for the TPU compilation model:

- same maxi-batch logic: prefetch ``--maxi-batch`` × ``--mini-batch``
  sentences, sort by target (or source) length, fill minibatches by sentence
  count (``--mini-batch``) or token budget (``--mini-batch-words``), then
  shuffle the minibatch order;
- NEW (the one real design change vs. the GPU reference, SURVEY.md §7):
  every emitted batch is padded to a shape from a small static **bucket
  table** — sequence lengths snap up to a bucket boundary and the sentence
  dimension snaps up to a divisor-friendly size — so XLA compiles a handful
  of programs instead of one per shape (the reference's --mini-batch-fit
  binary search becomes this table);
- background prefetch on a host thread (the reference's fetchBatches thread).

Batch layout is batch-major ``[batch, time]`` (the reference is time-major
``[time * batch]``; batch-major is the natural XLA layout).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .corpus import Corpus, SentenceTuple, CorpusState
from ..common import logging as log

# Default sequence-length buckets: fine steps early (NMT sentences are short),
# geometric later. Snapping to these keeps compile count ~O(10).
DEFAULT_LENGTH_BUCKETS = (8, 16, 24, 32, 48, 64, 96, 128, 192, 256, 384, 512,
                          768, 1024, 1536, 2048, 3072, 4096)


def bucket_length(n: int, buckets: Sequence[int] = DEFAULT_LENGTH_BUCKETS) -> int:
    for b in buckets:
        if n <= b:
            return b
    return ((n + 511) // 512) * 512


def bucket_batch_size(n: int, multiple: int = 8) -> int:
    """Snap sentence count up to a multiple (pad rows are fully masked)."""
    return max(multiple, ((n + multiple - 1) // multiple) * multiple)


def padded_batch_cost(n_rows: int, max_len: int,
                      length_buckets: Sequence[int] = DEFAULT_LENGTH_BUCKETS,
                      batch_multiple: int = 8) -> int:
    """Device cost (padded tokens) of a batch of ``n_rows`` sentences whose
    longest member has ``max_len`` tokens, under the bucketed static-shape
    table. This is the ONE cost model shared by the training-side token
    budget (_split_maxi flushes on ``rows * bucket_length``) and the serving
    scheduler (serving/scheduler.py) — serve-time batches must land on the
    same (rows, width) grid the jit cache was warmed on, or every odd batch
    costs a fresh XLA compile."""
    return (bucket_batch_size(n_rows, batch_multiple)
            * bucket_length(max_len, length_buckets))


@dataclasses.dataclass
class SubBatch:
    """One stream of a batch (reference: SubBatch: indices + mask)."""
    ids: np.ndarray    # [batch, time] int32, EOS-terminated, 0-padded
    mask: np.ndarray   # [batch, time] float32; 1 on real tokens (incl. EOS)

    @property
    def batch_size(self) -> int:
        return self.ids.shape[0]

    @property
    def batch_width(self) -> int:
        return self.ids.shape[1]

    @property
    def batch_words(self) -> int:
        return int(self.mask.sum())


@dataclasses.dataclass
class CorpusBatch:
    """A training batch across streams (reference: CorpusBatch)."""
    sub: List[SubBatch]               # [src..., trg]; trg is last
    sentence_ids: np.ndarray          # [batch] corpus line numbers (-1 = pad row)
    guided_alignment: Optional[np.ndarray] = None  # [batch, trg_len, src_len]
    data_weights: Optional[np.ndarray] = None      # [batch, trg_len] or [batch, 1]
    corpus_state: Optional[dict] = None   # post-window resume snapshot:
    # where the corpus stands once this batch's whole maxi window has
    # been applied — what do_save records for crash-safe resume

    @property
    def src(self) -> SubBatch:
        return self.sub[0]

    @property
    def trg(self) -> SubBatch:
        return self.sub[-1]

    @property
    def size(self) -> int:
        return int((self.sentence_ids >= 0).sum())

    @property
    def batch_size(self) -> int:
        return self.sub[0].batch_size

    @property
    def words(self) -> int:
        """Real target labels (the scheduler's label count)."""
        return self.trg.batch_words

    @property
    def src_words(self) -> int:
        return self.src.batch_words

    def shape_key(self) -> Tuple[int, ...]:
        return tuple(s.ids.shape[1] for s in self.sub) + (self.batch_size,)


def make_batch(tuples: Sequence[SentenceTuple], n_streams: int,
               length_buckets=DEFAULT_LENGTH_BUCKETS,
               batch_multiple: int = 8,
               pad_batch: bool = True,
               corpus_state: Optional[dict] = None,
               weighting_type: Optional[str] = None,
               fixed_rows: int = 0) -> CorpusBatch:
    """Pad a list of SentenceTuples into one fixed-shape CorpusBatch.

    `fixed_rows` > 0 pins the row count (extra rows fully masked): with a
    token budget the generator derives ONE canonical row count per width
    combo, collapsing the compiled-shape space to ~#length-buckets. Every
    distinct (widths, rows) shape costs a full XLA compile of the train
    step — on TPU that is tens of seconds (minutes over a remote tunnel),
    so an unbounded shape space is the single worst data-layer decision a
    TPU port can make. Masked pad rows cost only the FLOPs of an
    already-budget-sized batch."""
    n = len(tuples)
    if fixed_rows > 0:
        # n can overshoot fixed_rows by < batch_multiple (the budget check
        # flushes on padded tokens, fixed_rows is the budget floored to the
        # multiple); snapping up bounds the shape by the pre-canonical
        # worst case, so at most 2 row counts exist per width combo
        bsz = max(fixed_rows, bucket_batch_size(n, batch_multiple))
    else:
        bsz = bucket_batch_size(n, batch_multiple) if pad_batch else n
    subs: List[SubBatch] = []
    for s in range(n_streams):
        maxlen = max(len(t.streams[s]) for t in tuples)
        width = bucket_length(maxlen, length_buckets) if pad_batch else maxlen
        ids = np.zeros((bsz, width), dtype=np.int32)
        mask = np.zeros((bsz, width), dtype=np.float32)
        for b, t in enumerate(tuples):
            seq = t.streams[s]
            ids[b, : len(seq)] = seq
            mask[b, : len(seq)] = 1.0
        subs.append(SubBatch(ids, mask))
    sent_ids = np.full((bsz,), -1, dtype=np.int64)
    for b, t in enumerate(tuples):
        sent_ids[b] = t.idx

    guided = None
    if any(t.alignment is not None for t in tuples):
        tw, sw = subs[-1].ids.shape[1], subs[0].ids.shape[1]
        guided = np.zeros((bsz, tw, sw), dtype=np.float32)
        for b, t in enumerate(tuples):
            if t.alignment is not None:
                t.alignment.fill_dense(guided[b])

    weights = None
    if any(t.weights is not None for t in tuples):
        tw = subs[-1].ids.shape[1]
        # --data-weighting-type declares the level explicitly; without it,
        # infer word-level from multi-valued weight lines
        if weighting_type in ("word", "sentence"):
            word_level = weighting_type == "word"
        else:
            word_level = any(t.weights is not None and len(t.weights) > 1
                             for t in tuples)
        if word_level:
            weights = np.ones((bsz, tw), dtype=np.float32)
            for b, t in enumerate(tuples):
                if t.weights is not None:
                    w = t.weights[:tw]
                    weights[b, : len(w)] = w
        else:
            weights = np.ones((bsz, 1), dtype=np.float32)
            for b, t in enumerate(tuples):
                if t.weights is not None:
                    weights[b, 0] = t.weights[0]

    return CorpusBatch(subs, sent_ids, guided, weights, corpus_state)


class BatchGenerator:
    """Iterator of CorpusBatches with maxi-batch sorting and prefetch."""

    def __init__(self, corpus: Corpus, options=None,
                 mini_batch: int = 64, mini_batch_words: int = 0,
                 maxi_batch: int = 100, maxi_batch_sort: str = "trg",
                 shuffle_batches: Optional[bool] = None,
                 batch_multiple: int = 8, pad_batch: bool = True,
                 length_buckets=DEFAULT_LENGTH_BUCKETS,
                 prefetch: bool = True, seed: int = 1,
                 budget_scale=None):
        self.corpus = corpus
        if options is not None:
            mini_batch = int(options.get("mini-batch", mini_batch) or mini_batch)
            mini_batch_words = int(options.get("mini-batch-words", mini_batch_words) or 0)
            maxi_batch = int(options.get("maxi-batch", maxi_batch) or 1)
            maxi_batch_sort = options.get("maxi-batch-sort", maxi_batch_sort)
            seed = int(options.get("seed", seed)) or seed
            if shuffle_batches is None:
                shuffle_batches = options.get("shuffle", "data") in ("data", "batches")
        self.weighting_type = (str(options.get("data-weighting-type",
                                               "sentence"))
                               if options is not None
                               and options.get("data-weighting", None)
                               else None)
        self.mini_batch = max(1, mini_batch)
        self.mini_batch_words = mini_batch_words
        self.maxi_batch = max(1, maxi_batch)
        self.sort_key = maxi_batch_sort
        self.shuffle_batches = bool(shuffle_batches) and not corpus.inference
        self.batch_multiple = batch_multiple
        self.pad_batch = pad_batch
        self.length_buckets = length_buckets
        self.prefetch = prefetch
        # --mini-batch-warmup: a callable returning a scale in (0, 1] that
        # shrinks the effective batch early in training (checked per
        # maxi-window, so ramp-up is window-granular)
        self.budget_scale = budget_scale
        self._rs = np.random.RandomState(seed % (2**31))
        self.n_streams = len(corpus.vocabs)

    # -- batching core ------------------------------------------------------
    def _split_maxi(self, buf: List[SentenceTuple], state: dict) -> List[CorpusBatch]:
        if not buf:
            return []
        if self.sort_key == "trg":
            buf = sorted(buf, key=lambda t: (len(t.trg), len(t.src)))
        elif self.sort_key == "src":
            buf = sorted(buf, key=lambda t: (len(t.src), len(t.trg)))
        batches: List[CorpusBatch] = []
        cur: List[SentenceTuple] = []
        cur_maxlens = [0] * self.n_streams

        def flush():
            if not cur:
                return
            fixed = 0
            if self.pad_batch and words_budget > 0:
                # canonical row count per width combo: the shape a full
                # budget-sized batch of this width would have, so underfull
                # batches (maxi-window tails) reuse an existing compile
                # instead of minting a new (widths, rows) shape. Rounded
                # DOWN so the canonical shape never exceeds the worst case
                # --mini-batch-fit probed for this budget (batch_fit.py
                # rounds down too); the rows-counted path keeps its natural
                # sizes — inference entry points must not pay full-batch
                # compute for small inputs.
                w = bucket_length(max(len(t.trg) for t in cur),
                                  self.length_buckets)
                fixed = max(self.batch_multiple,
                            (words_budget // w) // self.batch_multiple
                            * self.batch_multiple)
            batches.append(make_batch(cur, self.n_streams, self.length_buckets,
                                      self.batch_multiple, self.pad_batch,
                                      corpus_state=state,
                                      weighting_type=self.weighting_type,
                                      fixed_rows=fixed))

        scale = 1.0
        if self.budget_scale is not None:
            scale = max(min(float(self.budget_scale()), 1.0), 1e-3)
        words_budget = max(int(self.mini_batch_words * scale), 1) \
            if self.mini_batch_words > 0 else 0
        rows_budget = max(int(self.mini_batch * scale), 1)
        for t in buf:
            lens = [len(s) for s in t.streams]
            new_maxlens = [max(a, b) for a, b in zip(cur_maxlens, lens)]
            n = len(cur) + 1
            if words_budget > 0:
                # token budget on padded target size (Marian counts labels);
                # use the bucketed width so the budget reflects real cost
                padded = bucket_length(new_maxlens[-1], self.length_buckets) \
                    if self.pad_batch else new_maxlens[-1]
                over = n * padded > words_budget and len(cur) > 0
            else:
                over = n > rows_budget
            if over:
                flush()
                cur = []
                new_maxlens = lens
            cur.append(t)
            cur_maxlens = new_maxlens
        flush()
        if self.shuffle_batches:
            self._rs.shuffle(batches)
        return batches

    def _generate(self) -> Iterator[CorpusBatch]:
        from ..common import faultpoints as fp
        buf: List[SentenceTuple] = []
        cap = self.maxi_batch * self.mini_batch
        it = iter(self.corpus)
        for t in it:
            buf.append(t)
            if len(buf) >= cap:
                # POST-window snapshot: the corpus position once every
                # sentence of this maxi window has been consumed. A save
                # taken after applying this window's batches resumes
                # HERE — exact at window boundaries, window-granular in
                # between (docs/ROBUSTNESS.md). The LIVE corpus.state is
                # no resume point at all: the prefetch thread runs it
                # arbitrarily far ahead of what training has applied.
                state = self.corpus.state.as_dict()
                for b in self._split_maxi(buf, state):
                    # chaos harness hook: a corpus/pipeline failure (bad
                    # shard, fs hiccup) surfaces HERE, mid-epoch — the
                    # crash-resume protocol must cover it like any kill
                    fp.fault_point("data.batch.next")
                    yield b
                buf = []
        state = self.corpus.state.as_dict()
        for b in self._split_maxi(buf, state):
            fp.fault_point("data.batch.next")
            yield b

    def __iter__(self) -> Iterator[CorpusBatch]:
        if not self.prefetch:
            yield from self._generate()
            return
        # background prefetch thread (reference: fetchBatches thread)
        q: "queue.Queue" = queue.Queue(maxsize=16)
        _END = object()
        err: List[BaseException] = []

        def worker():
            try:
                for b in self._generate():
                    q.put(b)
            except BaseException as e:  # propagate into consumer
                err.append(e)
            finally:
                q.put(_END)

        th = threading.Thread(target=worker, daemon=True, name="batchgen-prefetch")
        th.start()
        while True:
            b = q.get()
            if b is _END:
                break
            yield b
        th.join()
        if err:
            raise err[0]

    # -- stats (reference: GraphGroup::collectStats analogue) ---------------
    def stats(self, n: int = 1000) -> dict:
        """Sample shape distribution for logging/tuning."""
        shapes = {}
        for i, b in enumerate(self):
            if i >= n:
                break
            shapes[b.shape_key()] = shapes.get(b.shape_key(), 0) + 1
        return shapes
