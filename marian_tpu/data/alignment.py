"""Word alignments (reference: src/data/alignment.cpp :: WordAlignment) —
'0-0 1-2 ...' Pharaoh format parsing for guided-alignment training and
alignment output during decoding."""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

import numpy as np


@dataclasses.dataclass
class WordAlignment:
    points: List[Tuple[int, int, float]]  # (src, trg, prob)

    @classmethod
    def parse(cls, line: str) -> "WordAlignment":
        pts = []
        for tok in line.split():
            parts = tok.split("-")
            if len(parts) < 2:
                continue
            s, t = int(parts[0]), int(parts[1])
            p = float(parts[2]) if len(parts) > 2 else 1.0
            pts.append((s, t, p))
        return cls(pts)

    def fill_dense(self, out: np.ndarray) -> None:
        """out: [trg_len, src_len]; normalized per target word like Marian's
        guided-alignment matrix."""
        for s, t, p in self.points:
            if t < out.shape[0] and s < out.shape[1]:
                out[t, s] = p
        sums = out.sum(axis=-1, keepdims=True)
        np.divide(out, sums, out=out, where=sums > 0)

    def __str__(self) -> str:
        return " ".join(f"{s}-{t}" for s, t, _ in self.points)


def hard_alignment_from_soft(soft: np.ndarray, src_len: int, trg_len: int,
                             threshold: float = 1.0) -> WordAlignment:
    """Extract alignment points from a soft attention matrix [trg, src].
    threshold 1.0 → argmax per target word ('hard'); else keep points with
    prob >= threshold (reference: src/data/alignment.cpp ConvertSoftAlignToHardAlign)."""
    pts: List[Tuple[int, int, float]] = []
    m = soft[:trg_len, :src_len]
    if threshold >= 1.0:
        for t in range(trg_len):
            s = int(np.argmax(m[t]))
            pts.append((s, t, float(m[t, s])))
    else:
        for t in range(trg_len):
            for s in range(src_len):
                if m[t, s] >= threshold:
                    pts.append((s, t, float(m[t, s])))
    return WordAlignment(pts)
